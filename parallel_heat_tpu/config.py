"""Runtime configuration for the heat solver.

The reference compiles one binary per configuration via ``-D`` macros
(``NXPROB``, ``NYPROB``, ``STEPS``, ``STEP``, ``CONVERGE`` — see
``mpi/Makefile:1-25`` and ``mpi/mpi_heat_improved_persistent_stat.c:7-21``).
Here the same knobs are a runtime dataclass; one program serves every
configuration, and everything downstream of it is traced/compiled by XLA.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Optional, Tuple

# float16 is deliberately absent: Mosaic rejects f16 VMEM refs on TPU
# ("Unsupported type in mosaic dialect: 'f16'", probed on v5e) — bf16
# is the 2-byte storage dtype TPUs actually support.
_VALID_DTYPES = ("float32", "bfloat16", "float64")
_VALID_BACKENDS = ("auto", "jnp", "pallas")

# Time integrators (SEMANTICS.md "Implicit stepping"). "explicit" is
# the reference's forward-Euler Jacobi update, dt-capped by the von
# Neumann bound (stability_margin). The implicit schemes solve
# ``(I - theta*dt*L) u' = b`` each step with a geometric-multigrid
# V-cycle (ops/multigrid.py) and are unconditionally stable: the
# coefficients (cx/cy = alpha*dt/dx^2) may exceed the explicit bound
# by orders of magnitude — that IS the point (ROADMAP item 3).
_VALID_SCHEMES = ("explicit", "backward_euler", "crank_nicolson")

# --- cache-key partition (SEMANTICS.md "Statically verified contracts")
#
# Every HeatConfig field is classified exactly once, here. SEMANTIC
# fields select what the compiled simulation programs compute; they ARE
# the runner/executable cache key. OBSERVATION_ONLY fields configure
# host-side observers and orchestration (the guard, diagnostics,
# dispatch pipelining) and are provably stripped — reset to their
# defaults by ``solver._observer_free`` — before any
# ``solver._build_runner`` / executable-cache lookup, so enabling them
# can never fork a compiled program. The partition is machine-checked
# by ``parallel_heat_tpu.analysis`` rule HL101 (``tools/heatlint.py``):
# a new field that appears in NEITHER tuple fails CI, as does an
# observation-only field the strip site does not actually strip. Keep
# both tuples in declaration order.
SEMANTIC_FIELDS = (
    "nx", "ny", "nz", "cx", "cy", "cz",
    "steps", "converge", "eps", "check_interval",
    "dtype", "backend", "mesh_shape", "overlap", "halo_depth",
    "halo_overlap", "accumulate",
    "scheme", "mg_tol", "mg_cycles", "mg_smooth", "mg_levels",
    "mg_partition",
)
OBSERVATION_ONLY_FIELDS = ("guard_interval", "diag_interval",
                           "pipeline_depth")

# --- ensemble cache-key partition (SEMANTICS.md "Ensemble") -----------
#
# Same discipline as the HeatConfig partition above, for
# :class:`EnsembleConfig`: SEMANTIC fields select what the batched
# member programs compute and key the ensemble runner/executable
# caches; ORCHESTRATION fields shape only the host-side dispatch
# schedule (how many convergence windows run per dispatch, when the
# live batch is compacted) and are provably incapable of moving a
# member's trajectory — the compaction-invariance contract — so
# :meth:`EnsembleConfig.orchestration_free` resets them before any
# runner-cache lookup. Machine-checked by the same heatlint rule HL101
# (``analysis/contracts.py`` audits BOTH partitions): an unclassified
# EnsembleConfig field fails CI exactly like an unclassified
# HeatConfig field.
ENSEMBLE_SEMANTIC_FIELDS = ("members",)
ENSEMBLE_ORCHESTRATION_FIELDS = ("compact_threshold", "window_rounds")


@dataclass(frozen=True)
class EnsembleConfig:
    """Configuration of one batched ensemble run (``ensemble/``).

    ``members`` is B, the leading member-axis extent: B independent
    grids sharing one semantic :class:`HeatConfig` run in one compiled
    program. The other knobs are orchestration-only (see the partition
    comment above): they change dispatch boundaries and compaction
    points, never a member's arithmetic.
    """

    # The member-axis extent B (semantic: batched programs are shaped
    # by it and the runner cache keys on it).
    members: int = 1

    # Converge-mode compaction: when the live fraction of the CURRENT
    # batch drops strictly below this threshold at a window boundary,
    # finished members are parked and the live ones are compacted into
    # a smaller batch so stragglers stop paying for finished work.
    # None = never compact. At the default 0.5 each compaction at
    # least halves the batch, so a run recompiles at most O(log B)
    # batch sizes. Orchestration-only: member trajectories are
    # invariant to when (or whether) compaction happens — pinned by
    # tests/test_ensemble.py.
    compact_threshold: Optional[float] = 0.5

    # Converge-mode host-inspection cadence: how many check_interval
    # windows one dispatch advances before the host reads the
    # per-member verdicts (and may compact). Orchestration-only: a
    # member freezes at ITS convergence window regardless of how many
    # windows share a dispatch.
    window_rounds: int = 4

    def validate(self) -> "EnsembleConfig":
        if self.members < 1:
            raise ValueError(
                f"ensemble members must be >= 1, got {self.members}")
        if self.compact_threshold is not None and not (
                0.0 < self.compact_threshold <= 1.0):
            raise ValueError(
                f"compact_threshold must be in (0, 1] (or None to "
                f"disable compaction), got {self.compact_threshold}")
        if self.window_rounds < 1:
            raise ValueError(
                f"window_rounds must be >= 1, got {self.window_rounds}")
        return self

    def orchestration_free(self) -> "EnsembleConfig":
        """THE ensemble strip site (heatlint HL101, second audit):
        every orchestration-only field reset to its default — the
        config the batched runner caches key on."""
        defaults = {f.name: f.default for f in dataclasses.fields(self)}
        kw = {name: defaults[name] for name in ENSEMBLE_ORCHESTRATION_FIELDS
              if getattr(self, name) != defaults[name]}
        return self.replace(**kw) if kw else self

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "EnsembleConfig":
        return cls(**json.loads(s)).validate()

    def replace(self, **kw) -> "EnsembleConfig":
        return dataclasses.replace(self, **kw)


def divisible_factorizations(n_devices: int, shape) -> list:
    """Ordered ``len(shape)``-factorizations of ``n_devices`` whose
    factors divide the grid extents — the mesh shapes a given device
    count CAN legally take on a given grid. Used to make the
    non-divisible-mesh error actionable and by ``--mesh auto``'s
    fallback, so the two can never disagree about legality."""
    shape = tuple(shape)

    def rec(n, dims):
        if len(dims) == 1:
            return [(n,)] if dims[0] % n == 0 else []
        out = []
        for d in range(1, n + 1):
            if n % d == 0 and dims[0] % d == 0:
                out += [(d,) + rest for rest in rec(n // d, dims[1:])]
        return out

    return rec(n_devices, list(shape))


def multigrid_level_shapes(shape, mg_levels: Optional[int] = None,
                           min_interior: int = 3) -> list:
    """The geometric-multigrid level hierarchy for a 2D grid ``shape``
    (cells INCLUDING the Dirichlet boundary ring): ``[(nx0, ny0),
    (nx1, ny1), ...]`` finest first, each level's interior extent the
    floor-half of the previous (``m -> m // 2``, the vertex map
    ``fine = 2*coarse + 1`` that is well defined for ANY interior
    size), coarsening until either extent's interior would drop below
    ``min_interior`` or ``mg_levels`` levels exist.

    jax-free and the ONE source of truth for the hierarchy: the
    V-cycle builder (``ops/multigrid.py``), ``solver.explain`` and
    heatd's HBM admission pricing (``service/admission.py``) all call
    this, so the admitted estimate can never disagree with the arrays
    the solve actually allocates."""
    nx, ny = int(shape[0]), int(shape[1])
    levels = [(nx, ny)]
    while mg_levels is None or len(levels) < mg_levels:
        mi, ni = levels[-1][0] - 2, levels[-1][1] - 2
        mc, nc = mi // 2, ni // 2
        if mc < min_interior or nc < min_interior:
            break
        levels.append((mc + 2, nc + 2))
    return levels


def sublane_count(dtype: str) -> int:
    """TPU sublane tiling granularity for a storage dtype — the natural
    ``halo_depth`` for the Mosaic block kernel (kernel G). Mirrors
    ``ops.pallas_stencil._sub_rows`` (not imported there: this module
    must stay pallas-free and cheap)."""
    return 16 if dtype in ("bfloat16", "float16") else 8


@dataclass(frozen=True)
class HeatConfig:
    """Full runtime configuration of one simulation.

    Defaults mirror the reference's in-source macro defaults
    (``NXPROB=NYPROB=20``, ``STEPS``, ``STEP``/``CHECK_INTERVAL=20``,
    ``cx=cy=0.1`` — ``mpi/...stat.c:7-32``, ``cuda/cuda_heat.cu:7-23``).
    """

    # Grid extent (number of cells including the fixed Dirichlet boundary).
    nx: int = 20
    ny: int = 20
    nz: Optional[int] = None  # set for the 3D 7-point extension

    # Diffusion coefficients (Parms struct, mpi/...stat.c:29-32).
    cx: float = 0.1
    cy: float = 0.1
    cz: float = 0.1

    # Stepping. `steps` is the exact iteration count in fixed mode and the
    # upper bound in converge mode (CUDA semantics: `i < STEPS`,
    # cuda/cuda_heat.cu:204 — the reference MPI off-by-one `it <= STEPS`
    # is deliberately NOT replicated).
    steps: int = 100
    converge: bool = False
    eps: float = 1e-3
    check_interval: int = 20  # CHECK_INTERVAL, cuda/cuda_heat.cu:16

    # Numerics: storage dtype. Stencil arithmetic always accumulates in
    # float32 (the reference's own C/CUDA variants disagree about promotion,
    # SURVEY.md §2d.7 — we define pure-f32 accumulation as the semantics).
    dtype: str = "float32"

    # Compute backend for the per-shard stencil: "jnp" (XLA-fused slicing),
    # "pallas" (hand-written TPU kernel), or "auto" (pallas on TPU, jnp
    # elsewhere).
    backend: str = "auto"

    # Device mesh (dx, dy[, dz]) for spatial domain decomposition, or None
    # for single-device execution. The analog of MPI_Dims_create
    # (mpi/...stat.c:52).
    mesh_shape: Optional[Tuple[int, ...]] = None

    # Preserve the reference's interior/edge split so XLA can overlap the
    # halo ppermutes with interior compute (mpi/...stat.c:162-234).
    overlap: bool = True

    # Temporal blocking across the mesh: exchange K-deep halos once per
    # K steps instead of 1-deep halos every step (parallel/temporal.py)
    # — K x fewer collective rounds. 1 = the classic per-step exchange.
    # Applies to sharded runs (2D and 3D); results are bitwise identical
    # either way on the jnp path. None (the default) = auto: the solver
    # picks the Mosaic block kernel's depth (the dtype's sublane count)
    # when the resolved backend is pallas, a mesh is set, and the block
    # geometry admits — the best comm schedule should not be opt-in
    # (the reference's persistent-comms + overlap is likewise its
    # default, mpi/...stat.c:130-234) — and 1 otherwise. Explicit
    # values always win (``solver._resolve_halo_depth``).
    halo_depth: Optional[int] = None

    # Exchange/compute schedule of the sharded K-deep rounds
    # (SEMANTICS.md "Overlapped exchange"). The contract: every value
    # is BITWISE identical across all three schedules — the flag moves
    # collective hops off the compute critical path, never a bit.
    # - "phase":    phase-separated — each round's compute consumes the
    #               fully assembled exchange (every ppermute phase
    #               serializes before the first FLOP).
    # - "overlap":  deferred edge bands — the bulk update consumes only
    #               the block plus the FIRST exchange phase, so the
    #               later phase's ppermutes (row strips in 2D, x slabs
    #               in 3D) overlap the bulk compute; the thin bands are
    #               then computed from the arrived halos and spliced.
    # - "pipeline": double-buffered edge strips (2D pallas kernel-G
    #               rounds) — round r+1's ENTIRE exchange is built from
    #               thin band/panel passes of round r, so both ppermute
    #               phases stream while round r's bulk kernel computes.
    # - None/"auto" (default): "pipeline" where the kernel-G pipelined
    #               round is available and the TpuParams ICI model
    #               prices the hidden exchange above the extra edge
    #               compute, else "overlap". Geometry declines fall
    #               back one level (pipeline -> overlap -> phase-free
    #               monolithic jnp), reported by ``solver.explain``.
    # SEMANTIC: the flag selects the compiled dataflow schedule (a
    # different XLA program), so it keys the runner/executable caches
    # like ``overlap`` and ``backend`` — the bitwise-equality contract
    # is pinned by tests, not by cache sharing. Inert for unsharded
    # runs and for halo_depth == 1 (the per-step paths already overlap
    # via the ``overlap`` interior/edge split).
    halo_overlap: Optional[str] = None

    # Sub-f32 accumulation semantics (SEMANTICS.md). "storage" (default):
    # the state rounds to the storage dtype after EVERY step — K-step
    # temporal kernels are bit-identical to K single-step passes.
    # "f32chunk" (opt-in, 2D single-device, sub-f32 dtypes): the state
    # carries float32 across each K-step kernel chunk (K = the dtype's
    # sublane count, the temporal kernels' depth) and rounds to storage
    # ONCE per chunk — K-fold fewer rounding events, measurably lower
    # drift vs the f64 oracle, at a measured throughput cost (the f32
    # VMEM ping-pong halves the streaming budget). The reference never
    # resolved this choice — its MPI and CUDA variants silently disagree
    # about promotion (mpi/...stat.c:171-174 double literals vs
    # cuda/cuda_heat.cu:62 `2.0f`, SURVEY.md §2d.7); here it is an
    # explicit, priced flag.
    accumulate: str = "storage"

    # Time integrator (SEMANTICS.md "Implicit stepping"). "explicit"
    # (default) is the reference's forward-Euler Jacobi update, whose
    # dt is capped by the von Neumann bound (stability_margin). The
    # implicit schemes — "backward_euler" (first order) and
    # "crank_nicolson" (second order) — solve the linear system
    # ``(I - theta*L) u' = b`` every step with a sharded geometric-
    # multigrid V-cycle (ops/multigrid.py) and are unconditionally
    # stable: coefficients far past the explicit bound (100-1000x the
    # stable dt) take ONE step where explicit needed hundreds.
    # SEMANTIC: the scheme selects the compiled per-step program, so
    # it keys the runner/executable/result caches — cross-scheme cache
    # reuse is inadmissible by construction (service/cache.py).
    scheme: str = "explicit"

    # Implicit-solve knobs (inert — and REQUIRED to stay at their
    # defaults — for scheme="explicit"; validate() rejects non-default
    # values there so an inert knob can never fork a cache key).
    # mg_tol: per-step relative residual target of the V-cycle
    # iteration — cycles stop when ``max|b - A u| <= mg_tol * max|b|``
    # (the same max-norm machinery converge mode uses; max is exactly
    # associative, which keeps the verdict bitwise identical under any
    # sharding). Default 1e-3: the induced per-step solution error is
    # <= mg_tol * ||b|| (A's spectrum sits in [1, 1+4(cx+cy)]), orders
    # below the implicit schemes' temporal discretization error at the
    # large steps they exist for; tighten for converge runs with eps
    # near the solver floor.
    mg_tol: float = 1e-3
    # mg_cycles: hard V-cycle cap per step (the while_loop bound).
    mg_cycles: int = 50
    # mg_smooth: weighted-Jacobi pre- AND post-smoothing sweeps per
    # level per cycle (the V(nu,nu) shape; omega = 0.8).
    mg_smooth: int = 1
    # mg_levels: hierarchy depth cap; None = coarsen fully (every
    # halving until an interior extent would drop below 3 cells). The
    # level shapes are config.multigrid_level_shapes — one source of
    # truth shared with heatd's HBM admission pricing.
    mg_levels: Optional[int] = None
    # mg_partition: how the V-cycle executes on a SHARDED mesh
    # (SEMANTICS.md "Partitioned V-cycle").
    # - "replicated":  every device runs the full-grid cycle (the
    #   original spelling; bitwise the single-device run by
    #   construction).
    # - "partitioned": per-level padded shard_map blocks with a 1-deep
    #   halo exchange per smoothing sweep and per transfer seam
    #   (ops/multigrid_sharded.py); coarse levels below the
    #   profitability threshold agglomerate back to the replicated
    #   spelling.
    # - "auto" (default): partitioned where the prof/model ICI-vs-
    #   compute lanes say it wins (consultable at the "mg_partition"
    #   TuneDB site), replicated otherwise. Resolved once in
    #   solver._resolved, like halo_depth.
    # SEMANTIC: the flag selects the compiled step program, so it keys
    # the runner/executable caches. Inert — and required to stay
    # "auto" — for scheme="explicit" and for unsharded implicit runs
    # (a non-default value there would fork cache keys while changing
    # nothing the program computes).
    mg_partition: str = "auto"

    # Runtime blow-up guard (SEMANTICS.md "Runtime guard"): steps between
    # on-device isfinite-all checks of the evolving grid. None (default)
    # = off — no guard program is ever built, and outputs are bitwise
    # those of a guard-free run. When set, `solve_stream` evaluates the
    # fused reduction at the first chunk boundary at-or-after each
    # multiple of `guard_interval` (this is the FIXED-STEP failure
    # detector the reference lacks — converge mode already inspects its
    # residual), and `solve` checks the final grid once. The guard is
    # observation-only: it reads the grid between dispatches, never
    # writes, and is stripped from the compiled program's cache key, so
    # enabling it cannot shift a bit of the simulation. The run
    # supervisor (`parallel_heat_tpu.supervisor`) layers rollback/retry
    # on top of the same check.
    guard_interval: Optional[int] = None

    # In-run numerics diagnostics (SEMANTICS.md "Runtime guard"): steps
    # between fused on-device grid-stats samples (min, max, total heat
    # content, L2/L-inf update residual — `solver.grid_stats`). None
    # (default) = off. When set, `solve_stream` samples at the first
    # chunk boundary at-or-after each multiple of `diag_interval` (and
    # at the final chunk), attaches the sample to
    # `HeatResult.diagnostics`, and emits a `diagnostics` telemetry
    # event when a sink is attached; `solve` samples the final grid
    # once. Observation-only, exactly like the guard: the reduction
    # reads between dispatches, never writes, and `diag_interval` is
    # stripped from the compiled-program cache keys, so enabled runs
    # share (and are bitwise) the undiagnosed executables. Cost: the
    # fused reduction per sample plus ONE retained grid copy (the
    # previous sample, the update-residual baseline).
    diag_interval: Optional[int] = None

    # Stream dispatch pipelining (SEMANTICS.md "Pipelined stream"):
    # how many chunks `solve_stream` keeps in flight on the device at
    # once. None (default) = auto: 2 (dispatch chunk n+1 immediately
    # after chunk n's dispatch returns, drain chunk n's observers while
    # n+1 computes) for fixed-step runs on an accelerator backend, 1
    # otherwise — converge runs cannot dispatch ahead of the on-device
    # convergence verdict, and on CPU the host and "device" share
    # cores, so there is no idle accelerator to keep busy (depth 2
    # there is a measured ~10% pessimization — the bench stream512
    # row prices it; same platform-aware shape as backend="auto"). Pipelining is
    # dispatch-order only: yielded grids (donation-protected copies at
    # depth > 1), guard/diag observations, compiled programs, and
    # checkpoint bytes are identical to the depth-1 loop; only the
    # per-chunk wall-clock bracket changes (drain-to-drain instead of
    # dispatch-to-ready). Stripped from runner/executable cache keys
    # like the guard, so every depth shares one compiled-program
    # family. Explicit values: >= 1; > 1 with converge=True is a loud
    # error rather than a silent fallback.
    pipeline_depth: Optional[int] = None

    # --- derived helpers -------------------------------------------------

    @property
    def ndim(self) -> int:
        return 3 if self.nz is not None else 2

    @property
    def shape(self) -> Tuple[int, ...]:
        if self.ndim == 3:
            return (self.nx, self.ny, self.nz)
        return (self.nx, self.ny)

    @property
    def coefficients(self) -> Tuple[float, ...]:
        if self.ndim == 3:
            return (self.cx, self.cy, self.cz)
        return (self.cx, self.cy)

    def mesh_or_unit(self) -> Tuple[int, ...]:
        """The mesh shape, defaulting to the all-ones (single device) mesh."""
        if self.mesh_shape is None:
            return (1,) * self.ndim
        return tuple(self.mesh_shape)

    def block_shape(self) -> Tuple[int, ...]:
        """Per-device block extent under the mesh decomposition."""
        return tuple(n // d for n, d in zip(self.shape, self.mesh_or_unit()))

    def stability_margin(self) -> float:
        """``1/2 - sum(coefficients)`` — the von Neumann stability margin.

        The explicit Jacobi scheme amplifies the highest spatial mode by
        ``1 - 4*sum(c)*sin^2(...)``; it stays bounded iff the
        coefficient sum is <= 1/2. Negative margin means the run will
        blow up to inf/NaN (the reference never checks: its fixed
        cx=cy=0.1 sits safely at margin 0.3).
        """
        return 0.5 - sum(self.coefficients)

    def validate(self) -> "HeatConfig":
        if self.scheme == "explicit" and self.stability_margin() < 0.0:
            # Warn (never error: instability is sometimes the thing
            # being studied) from the one place every entry point —
            # solve, solve_stream, the CLI, make_initial_grid — passes
            # through. Implicit schemes are unconditionally stable, so
            # the bound does not apply there — and the warning names
            # that escape hatch, because "reduce dt" is the wrong fix
            # when the user WANTS the big step.
            import warnings

            # No stacklevel: attributing the warning to this fixed line
            # lets the default filter deduplicate it across the several
            # validate() calls one run makes (CLI, solve, per chunk).
            warnings.warn(
                f"coefficient sum {sum(self.coefficients):g} exceeds the "
                f"stability bound 1/2 — the explicit scheme will diverge "
                f"(values blow up to inf); to take steps this large, "
                f"switch to the implicit integrator: "
                f"scheme='backward_euler' (--scheme backward_euler), "
                f"which is unconditionally stable",
                RuntimeWarning,
            )
        if self.nx < 3 or self.ny < 3 or (self.nz is not None and self.nz < 3):
            raise ValueError(
                f"grid must be at least 3 cells per axis, got {self.shape}"
            )
        if self.steps < 0:
            raise ValueError(f"steps must be >= 0, got {self.steps}")
        if self.converge and self.check_interval < 1:
            raise ValueError(
                f"check_interval must be >= 1, got {self.check_interval}"
            )
        if self.converge and self.eps <= 0.0:
            raise ValueError(f"eps must be > 0, got {self.eps}")
        if self.dtype not in _VALID_DTYPES:
            raise ValueError(
                f"dtype must be one of {_VALID_DTYPES}, got {self.dtype!r}"
            )
        if self.dtype == "float64":
            import jax

            if not jax.config.jax_enable_x64:
                raise ValueError(
                    "dtype='float64' requires jax_enable_x64 (otherwise JAX "
                    "silently computes in float32)"
                )
        if self.backend not in _VALID_BACKENDS:
            raise ValueError(
                f"backend must be one of {_VALID_BACKENDS}, got {self.backend!r}"
            )
        mesh = self.mesh_or_unit()
        if len(mesh) != self.ndim:
            raise ValueError(
                f"mesh_shape {mesh} rank does not match grid rank {self.ndim}"
            )
        if any(d < 1 for d in mesh):
            raise ValueError(f"mesh_shape entries must be >= 1, got {mesh}")
        for n, d, name in zip(self.shape, mesh, "xyz"):
            if n % d != 0:
                # The reference silently assumes divisibility
                # (mpi/...stat.c:72-73, SURVEY.md §2d.6); we make it
                # loud AND actionable: same device count, the mesh
                # shapes that DO divide this grid — or, when none
                # exists, the nearest grid sizes that would.
                n_dev = 1
                for dd in mesh:
                    n_dev *= dd
                valid = divisible_factorizations(n_dev, self.shape)
                if valid:
                    hint = (f"; valid {n_dev}-device mesh shapes for "
                            f"this grid: "
                            + ", ".join(str(v) for v in valid[:8])
                            + (" ..." if len(valid) > 8 else ""))
                else:
                    near = []
                    for nn, dd, nm in zip(self.shape, mesh, "xyz"):
                        if nn % dd != 0:
                            lo, hi = (nn // dd) * dd, (nn // dd + 1) * dd
                            near.append(f"n{nm}={hi}" if lo == 0
                                        else f"n{nm}={lo} or {hi}")
                    hint = (f"; no factorization of {n_dev} devices "
                            f"divides this grid — nearest divisible "
                            f"sizes: " + ", ".join(near))
                raise ValueError(
                    f"grid n{name}={n} is not divisible by mesh "
                    f"d{name}={d}" + hint
                )
        if self.halo_depth is not None and self.halo_depth < 1:
            raise ValueError(
                f"halo_depth must be >= 1 (or None for auto), got "
                f"{self.halo_depth}"
            )
        if self.halo_depth is not None and self.halo_depth > 1:
            sub = sublane_count(self.dtype)
            is_f64 = self.dtype == "float64"
            if self.backend == "pallas" and self.halo_depth != sub \
                    and not is_f64 and self.ndim == 2:
                # The 2D Mosaic block kernel (G) only exists at depth
                # == the dtype's sublane count; any other depth would
                # silently fall back to jnp rounds against an explicit
                # pallas request. 3D is exempt: kernel H's slab windows
                # are alignment-free in the slab dim, so it accepts any
                # depth the geometry admits (declines fall back like
                # geometry declines). float64 is exempt: Mosaic has no
                # 64-bit types, so the solver routes f64 to the jnp
                # path for EVERY backend choice — the jnp rounds
                # support any depth.
                raise ValueError(
                    f"backend='pallas' with halo_depth > 1 requires "
                    f"halo_depth == {sub} for dtype {self.dtype} (the "
                    f"Mosaic block kernel's depth); other depths run "
                    f"the jnp rounds — use backend='jnp' or 'auto'"
                )
            if any(d > 1 for d in mesh):
                bmin = min(self.block_shape())
                if self.halo_depth > bmin:
                    # A deeper halo than one block would need multi-hop
                    # exchanges (neighbors only own block-width strips).
                    raise ValueError(
                        f"halo_depth={self.halo_depth} exceeds the "
                        f"smallest block extent {bmin}"
                    )
        if self.halo_overlap not in (None, "auto", "phase", "overlap",
                                     "pipeline"):
            raise ValueError(
                f"halo_overlap must be one of 'auto'/None, 'phase', "
                f"'overlap', 'pipeline', got {self.halo_overlap!r}"
            )
        if self.guard_interval is not None and self.guard_interval < 1:
            raise ValueError(
                f"guard_interval must be >= 1 (or None to disable the "
                f"runtime guard), got {self.guard_interval}"
            )
        if self.diag_interval is not None and self.diag_interval < 1:
            raise ValueError(
                f"diag_interval must be >= 1 (or None to disable grid "
                f"diagnostics), got {self.diag_interval}"
            )
        if self.pipeline_depth is not None:
            if self.pipeline_depth < 1:
                raise ValueError(
                    f"pipeline_depth must be >= 1 (or None for auto), "
                    f"got {self.pipeline_depth}"
                )
            if self.pipeline_depth > 1 and self.converge:
                raise ValueError(
                    "pipeline_depth > 1 is fixed-step only: converge "
                    "mode must read each chunk's on-device convergence "
                    "verdict before dispatching the next chunk, so "
                    "dispatch-ahead would speculate past the stopping "
                    "point (use pipeline_depth=1 or drop the flag — "
                    "auto already resolves converge runs to 1)"
                )
        if self.accumulate not in ("storage", "f32chunk"):
            raise ValueError(
                f"accumulate must be 'storage' or 'f32chunk', got "
                f"{self.accumulate!r}"
            )
        if self.scheme not in _VALID_SCHEMES:
            raise ValueError(
                f"scheme must be one of {_VALID_SCHEMES}, got "
                f"{self.scheme!r}")
        if self.mg_tol <= 0.0:
            raise ValueError(f"mg_tol must be > 0, got {self.mg_tol}")
        if self.mg_cycles < 1:
            raise ValueError(
                f"mg_cycles must be >= 1, got {self.mg_cycles}")
        if self.mg_smooth < 1:
            raise ValueError(
                f"mg_smooth must be >= 1, got {self.mg_smooth}")
        if self.mg_levels is not None and self.mg_levels < 1:
            raise ValueError(
                f"mg_levels must be >= 1 (or None for full "
                f"coarsening), got {self.mg_levels}")
        if self.mg_partition not in ("auto", "replicated",
                                     "partitioned"):
            raise ValueError(
                f"mg_partition must be one of 'auto', 'replicated', "
                f"'partitioned', got {self.mg_partition!r}")
        if self.scheme == "explicit":
            # Inert knobs must stay at their defaults (loud declines
            # over silent no-ops): a non-default mg_* on an explicit
            # config would fork runner/result-cache keys while
            # changing nothing the program computes.
            defaults = HeatConfig()
            off = [n for n in ("mg_tol", "mg_cycles", "mg_smooth",
                               "mg_levels", "mg_partition")
                   if getattr(self, n) != getattr(defaults, n)]
            if off:
                raise ValueError(
                    f"{', '.join(off)} only apply to the implicit "
                    f"schemes (scheme='backward_euler' or "
                    f"'crank_nicolson'); scheme='explicit' takes no "
                    f"multigrid knobs")
        else:
            if self.ndim != 2:
                raise ValueError(
                    f"scheme={self.scheme!r} is 2D-only in this "
                    f"build: the 3D multigrid transfer operators are "
                    f"not yet built (the 5-point V-cycle is — use "
                    f"nz=None)")
            if self.accumulate != "storage":
                raise ValueError(
                    "accumulate='f32chunk' applies to the explicit "
                    "temporal kernels only; the implicit V-cycle "
                    "already carries float32 through every step solve "
                    "and rounds to storage once per step")
            if self.halo_depth is not None and self.halo_depth != 1:
                raise ValueError(
                    f"halo_depth={self.halo_depth} is an explicit-"
                    f"scheme exchange schedule (K steps per collective "
                    f"round); the implicit V-cycle exchanges per "
                    f"smoothing sweep — drop the flag (auto resolves "
                    f"implicit runs to 1)")
            if self.halo_overlap not in (None, "auto"):
                raise ValueError(
                    f"halo_overlap={self.halo_overlap!r} schedules the "
                    f"explicit temporal rounds; it does not apply to "
                    f"scheme={self.scheme!r} — drop the flag")
            if not self.overlap:
                # Same inert-knob rule as the mg_* defaults on
                # explicit configs: `overlap` schedules the explicit
                # per-step interior/edge split, which the implicit
                # V-cycle never builds — a non-default value would
                # fork SEMANTIC cache/runner keys while changing
                # nothing the program computes.
                raise ValueError(
                    "overlap=False schedules the explicit per-step "
                    "interior/edge split; it does not apply to "
                    f"scheme={self.scheme!r} — drop the flag")
            if (self.mg_partition != "auto"
                    and not any(d > 1 for d in mesh)):
                # Same inert-knob rule: partition modes only select a
                # program on a sharded mesh — a single-device config
                # has exactly one V-cycle spelling.
                raise ValueError(
                    f"mg_partition={self.mg_partition!r} selects the "
                    f"sharded V-cycle spelling; it does not apply "
                    f"without a device mesh — drop the flag (auto)")
            if len(multigrid_level_shapes(self.shape,
                                          self.mg_levels)) < 1:
                raise ValueError(  # unreachable (level 0 always exists)
                    "empty multigrid hierarchy")
        if self.accumulate == "f32chunk":
            # Loud declines over silent fallbacks: the flag changes the
            # numerics contract, so paths that cannot honor it refuse.
            if self.dtype != "bfloat16":
                raise ValueError(
                    f"accumulate='f32chunk' only applies to sub-f32 "
                    f"storage dtypes (got {self.dtype}: f32+ storage "
                    f"already carries full f32 state — SEMANTICS.md)"
                )
            if self.ndim != 2:
                raise ValueError(
                    "accumulate='f32chunk' is 2D-only (the priced "
                    "config-4 capability); 3D chunked accumulation is "
                    "not yet built"
                )
            if any(d > 1 for d in mesh):
                raise ValueError(
                    "accumulate='f32chunk' is single-device only: "
                    "sharded temporal rounds exchange storage-dtype "
                    "halos, so the chunk carry cannot stay f32 across "
                    "the mesh"
                )
        return self

    # --- (de)serialization ----------------------------------------------

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "HeatConfig":
        d = json.loads(s)
        if d.get("mesh_shape") is not None:
            d["mesh_shape"] = tuple(d["mesh_shape"])
        return cls(**d).validate()

    def replace(self, **kw) -> "HeatConfig":
        return dataclasses.replace(self, **kw)
