"""parallel_heat_tpu — a TPU-native heat-diffusion simulation framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``manospits/parallel_heat`` (MPI C + CUDA, see ``/root/reference``):

- 2D 5-point (and 3D 7-point) Jacobi heat stencils, double-buffered,
  Dirichlet boundary (reference: ``cuda/cuda_heat.cu:57-65``,
  ``mpi/mpi_heat_improved_persistent_stat.c:166-176``).
- Fixed-step and epsilon-convergence modes (``cuda/cuda_heat.cu:219-236``).
- 2D spatial domain decomposition with halo exchange over a TPU ICI mesh
  (``shard_map`` + ``lax.ppermute`` — replacing the reference's persistent
  MPI sends, ``mpi/...stat.c:130-161``).
- Compute/communication overlap via an interior/edge split
  (``mpi/...stat.c:162-234``).
- On-device fused convergence reduction (``lax.pmax`` — replacing the
  CUDA shared-memory flag trees + host polling, ``cuda/cuda_heat.cu:66-137``).
- Pallas VMEM stencil kernels for the hot loop.
- Golden-file compatible ``.dat`` I/O (``mpi/...stat.c:326-341``).
"""

from parallel_heat_tpu.config import EnsembleConfig, HeatConfig
from parallel_heat_tpu.solver import (
    HeatResult,
    grid_all_finite,
    grid_stats,
    make_initial_grid,
    solve,
    solve_stream,
)
from parallel_heat_tpu.models import HeatPlate2D, HeatPlate3D
from parallel_heat_tpu.parallel.coordinator import PeerLostError
from parallel_heat_tpu.supervisor import (
    EXIT_PERMANENT_FAILURE,
    EXIT_PREEMPTED,
    PermanentFailure,
    SupervisorPolicy,
    SupervisorResult,
    run_supervised,
)
from parallel_heat_tpu.utils.telemetry import Telemetry

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy ensemble surface: the engine pulls in the solver's kernel
    # machinery, which jax-free consumers of this package's config
    # vocabulary (the service admission gate) must not pay for.
    if name in ("EnsembleSolver", "EnsembleResult"):
        from parallel_heat_tpu.ensemble import engine

        return getattr(engine, name)
    if name == "run_ensemble_supervised":
        from parallel_heat_tpu.ensemble import supervised

        return supervised.run_ensemble_supervised
    raise AttributeError(name)


__all__ = [
    "HeatConfig",
    "EnsembleConfig",
    "EnsembleSolver",
    "EnsembleResult",
    "run_ensemble_supervised",
    "HeatResult",
    "solve",
    "solve_stream",
    "make_initial_grid",
    "grid_all_finite",
    "grid_stats",
    "run_supervised",
    "SupervisorPolicy",
    "SupervisorResult",
    "PermanentFailure",
    "PeerLostError",
    "EXIT_PREEMPTED",
    "EXIT_PERMANENT_FAILURE",
    "Telemetry",
    "HeatPlate2D",
    "HeatPlate3D",
    "__version__",
]
