"""Sharded-vs-single equivalence on the 8-device virtual CPU mesh.

The per-cell arithmetic uses identical expression trees in the single-
device and per-block paths, so results must match *bitwise* in f32 —
commutativity (not associativity) is the only reordering involved.
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.solver import make_initial_grid

MESHES = [(1, 1), (2, 1), (1, 2), (2, 2), (2, 4), (4, 2), (8, 1), (1, 8)]


def _single(nx, ny, **kw):
    return solve(HeatConfig(nx=nx, ny=ny, backend="jnp", **kw))


@pytest.mark.parametrize("mesh", MESHES)
def test_fixed_steps_sharded_equals_single(mesh):
    kw = dict(steps=30)
    want = _single(16, 16, **kw).to_numpy()
    got = solve(
        HeatConfig(nx=16, ny=16, backend="jnp", mesh_shape=mesh, **kw)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4), (8, 1)])
@pytest.mark.parametrize("overlap", [True, False])
def test_overlap_and_padded_paths_agree(mesh, overlap):
    want = _single(24, 16, steps=25).to_numpy()
    got = solve(
        HeatConfig(nx=24, ny=16, steps=25, backend="jnp",
                   mesh_shape=mesh, overlap=overlap)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4)])
def test_converge_sharded_equals_single(mesh):
    kw = dict(steps=2000, converge=True, check_interval=20, eps=1e-3)
    want = _single(20, 20, **kw)
    got = solve(
        HeatConfig(nx=20, ny=20, backend="jnp", mesh_shape=mesh, **kw)
    )
    assert got.converged == want.converged is True
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_sharded_initial_grid_matches_single():
    cfg_s = HeatConfig(nx=32, ny=32, mesh_shape=(2, 4))
    cfg_1 = HeatConfig(nx=32, ny=32)
    np.testing.assert_allclose(
        np.asarray(make_initial_grid(cfg_s)),
        np.asarray(make_initial_grid(cfg_1)),
        rtol=1e-6,
    )


def test_sharded_result_is_actually_sharded():
    cfg = HeatConfig(nx=16, ny=16, steps=4, backend="jnp",
                     mesh_shape=(2, 4))
    res = solve(cfg)
    assert len(res.grid.sharding.device_set) == 8


@pytest.mark.parametrize("mesh", [(2, 2)])
def test_nonsquare_blocks(mesh):
    want = _single(12, 36, steps=17).to_numpy()
    got = solve(
        HeatConfig(nx=12, ny=36, steps=17, backend="jnp", mesh_shape=mesh)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)
