"""Sharded-vs-single equivalence on the 8-device virtual CPU mesh.

The per-cell arithmetic uses identical expression trees in the single-
device and per-block paths, so results must match *bitwise* in f32 —
commutativity (not associativity) is the only reordering involved.
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.solver import make_initial_grid

MESHES = [(1, 1), (2, 1), (1, 2), (2, 2), (2, 4), (4, 2), (8, 1), (1, 8)]


def _single(nx, ny, **kw):
    return solve(HeatConfig(nx=nx, ny=ny, backend="jnp", **kw))


@pytest.mark.parametrize("mesh", MESHES)
def test_fixed_steps_sharded_equals_single(mesh):
    kw = dict(steps=30)
    want = _single(16, 16, **kw).to_numpy()
    got = solve(
        HeatConfig(nx=16, ny=16, backend="jnp", mesh_shape=mesh, **kw)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4), (8, 1)])
@pytest.mark.parametrize("overlap", [True, False])
def test_overlap_and_padded_paths_agree(mesh, overlap):
    want = _single(24, 16, steps=25).to_numpy()
    got = solve(
        HeatConfig(nx=24, ny=16, steps=25, backend="jnp",
                   mesh_shape=mesh, overlap=overlap)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4)])
def test_converge_sharded_equals_single(mesh):
    kw = dict(steps=2000, converge=True, check_interval=20, eps=1e-3)
    want = _single(20, 20, **kw)
    got = solve(
        HeatConfig(nx=20, ny=20, backend="jnp", mesh_shape=mesh, **kw)
    )
    assert got.converged == want.converged is True
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_sharded_initial_grid_matches_single():
    cfg_s = HeatConfig(nx=32, ny=32, mesh_shape=(2, 4))
    cfg_1 = HeatConfig(nx=32, ny=32)
    np.testing.assert_allclose(
        np.asarray(make_initial_grid(cfg_s)),
        np.asarray(make_initial_grid(cfg_1)),
        rtol=1e-6,
    )


def test_sharded_result_is_actually_sharded():
    cfg = HeatConfig(nx=16, ny=16, steps=4, backend="jnp",
                     mesh_shape=(2, 4))
    res = solve(cfg)
    assert len(res.grid.sharding.device_set) == 8


@pytest.mark.parametrize("mesh", [(2, 2)])
def test_nonsquare_blocks(mesh):
    want = _single(12, 36, steps=17).to_numpy()
    got = solve(
        HeatConfig(nx=12, ny=36, steps=17, backend="jnp", mesh_shape=mesh)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_mesh_topology_aware_device_order(monkeypatch):
    """make_heat_mesh consults the physical topology (via
    mesh_utils.create_device_mesh) when the mesh spans all devices on a
    TPU platform — faked here so the assignment path runs on CPU."""
    import numpy as np
    import jax
    from jax.experimental import mesh_utils
    from parallel_heat_tpu.parallel import mesh as m

    perm = list(reversed(jax.devices()))
    calls = {}

    def fake_create(shape, devices=None):
        calls["shape"] = tuple(shape)
        return np.asarray(perm).reshape(shape)

    monkeypatch.setattr(m, "_use_topology_order", lambda avail: True)
    monkeypatch.setattr(mesh_utils, "create_device_mesh", fake_create)
    built = m.make_heat_mesh((2, 4))
    assert calls["shape"] == (2, 4)
    assert list(built.devices.flat) == perm
    assert built.axis_names == ("x", "y")


def test_mesh_partial_and_explicit_device_order():
    # Partial meshes (fewer devices than available) and explicit device
    # lists keep enumeration/user order — no topology reorder to rely on.
    import jax
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh

    devs = jax.devices()
    built = make_heat_mesh((2, 2))
    assert list(built.devices.flat) == devs[:4]
    pick = [devs[3], devs[1], devs[0], devs[2]]
    built = make_heat_mesh((2, 2), devices=pick)
    assert list(built.devices.flat) == pick


def test_mesh_topology_fallback_on_unfactorable(monkeypatch):
    # create_device_mesh refusals (unfactorable shape/topology) fall
    # back to enumeration order instead of erroring.
    import jax
    from jax.experimental import mesh_utils
    from parallel_heat_tpu.parallel import mesh as m

    def refuse(shape, devices=None):
        raise ValueError("cannot factor topology")

    monkeypatch.setattr(m, "_use_topology_order", lambda avail: True)
    monkeypatch.setattr(mesh_utils, "create_device_mesh", refuse)
    built = m.make_heat_mesh((2, 4))
    assert list(built.devices.flat) == jax.devices()
