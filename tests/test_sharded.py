"""Sharded-vs-single equivalence on the 8-device virtual CPU mesh.

The per-cell arithmetic uses identical expression trees in the single-
device and per-block paths, so results must match *bitwise* in f32 —
commutativity (not associativity) is the only reordering involved.
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.solver import make_initial_grid

MESHES = [(1, 1), (2, 1), (1, 2), (2, 2), (2, 4), (4, 2), (8, 1), (1, 8)]


def _single(nx, ny, **kw):
    return solve(HeatConfig(nx=nx, ny=ny, backend="jnp", **kw))


@pytest.mark.parametrize("mesh", MESHES)
def test_fixed_steps_sharded_equals_single(mesh):
    kw = dict(steps=30)
    want = _single(16, 16, **kw).to_numpy()
    got = solve(
        HeatConfig(nx=16, ny=16, backend="jnp", mesh_shape=mesh, **kw)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4), (8, 1)])
@pytest.mark.parametrize("overlap", [True, False])
def test_overlap_and_padded_paths_agree(mesh, overlap):
    want = _single(24, 16, steps=25).to_numpy()
    got = solve(
        HeatConfig(nx=24, ny=16, steps=25, backend="jnp",
                   mesh_shape=mesh, overlap=overlap)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4)])
def test_converge_sharded_equals_single(mesh):
    kw = dict(steps=2000, converge=True, check_interval=20, eps=1e-3)
    want = _single(20, 20, **kw)
    got = solve(
        HeatConfig(nx=20, ny=20, backend="jnp", mesh_shape=mesh, **kw)
    )
    assert got.converged == want.converged is True
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_sharded_initial_grid_matches_single():
    cfg_s = HeatConfig(nx=32, ny=32, mesh_shape=(2, 4))
    cfg_1 = HeatConfig(nx=32, ny=32)
    np.testing.assert_allclose(
        np.asarray(make_initial_grid(cfg_s)),
        np.asarray(make_initial_grid(cfg_1)),
        rtol=1e-6,
    )


def test_sharded_result_is_actually_sharded():
    cfg = HeatConfig(nx=16, ny=16, steps=4, backend="jnp",
                     mesh_shape=(2, 4))
    res = solve(cfg)
    assert len(res.grid.sharding.device_set) == 8


def test_prepare_initial_host_grid_lands_sharded():
    # A caller-supplied HOST grid (gathered-.npz resume, any NumPy
    # array) must be placed with the mesh's NamedSharding before the
    # run — per-shard slices, never a full-grid single-device commit
    # (the reference's O(N^2)-per-rank quirk, SURVEY §2d.1).
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parallel_heat_tpu.parallel.mesh import make_heat_mesh
    from parallel_heat_tpu.solver import _prepare_initial

    cfg = HeatConfig(nx=32, ny=16, mesh_shape=(2, 4), steps=5)
    host = np.asarray(make_initial_grid(HeatConfig(nx=32, ny=16)))
    prepared = _prepare_initial(cfg, host)
    mesh = make_heat_mesh((2, 4))
    want = NamedSharding(mesh, P(*mesh.axis_names))
    assert prepared.sharding == want
    # No device holds more than its block.
    for s in prepared.addressable_shards:
        assert s.data.shape == (16, 4)
    # An f64 host grid resuming into a bf16 run is cast without a
    # device-side full-grid commit and still lands sharded.
    prepared16 = _prepare_initial(cfg.replace(dtype="bfloat16"),
                                  host.astype(np.float64))
    assert prepared16.dtype == np.dtype("bfloat16")
    assert prepared16.sharding == want
    # And the solve from a host initial equals the solve from the
    # born-sharded initial, bitwise.
    a = solve(cfg, initial=host).to_numpy()
    b = solve(cfg).to_numpy()
    np.testing.assert_array_equal(a, b)


def test_prepare_initial_reshards_device_array():
    # A single-device (or differently-sharded) jax.Array initial is
    # redistributed to the mesh sharding; donation safety: the
    # caller's array survives the solve.
    import jax

    from parallel_heat_tpu.solver import _prepare_initial

    cfg = HeatConfig(nx=16, ny=16, mesh_shape=(2, 2), steps=3)
    single = make_initial_grid(HeatConfig(nx=16, ny=16))
    prepared = _prepare_initial(cfg, single)
    assert len(prepared.sharding.device_set) == 4
    res = solve(cfg, initial=single)
    # the caller's buffer was not donated away
    np.testing.assert_array_equal(np.asarray(single),
                                  np.asarray(make_initial_grid(
                                      HeatConfig(nx=16, ny=16))))
    np.testing.assert_array_equal(res.to_numpy(),
                                  solve(cfg).to_numpy())


@pytest.mark.parametrize("mesh", [(2, 2)])
def test_nonsquare_blocks(mesh):
    want = _single(12, 36, steps=17).to_numpy()
    got = solve(
        HeatConfig(nx=12, ny=36, steps=17, backend="jnp", mesh_shape=mesh)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_mesh_topology_aware_device_order(monkeypatch):
    """make_heat_mesh consults the physical topology (via
    mesh_utils.create_device_mesh) when the mesh spans all devices on a
    TPU platform — faked here so the assignment path runs on CPU."""
    import numpy as np
    import jax
    from jax.experimental import mesh_utils
    from parallel_heat_tpu.parallel import mesh as m

    perm = list(reversed(jax.devices()))
    calls = {}

    def fake_create(shape, devices=None):
        calls["shape"] = tuple(shape)
        return np.asarray(perm).reshape(shape)

    monkeypatch.setattr(m, "_use_topology_order", lambda avail: True)
    monkeypatch.setattr(mesh_utils, "create_device_mesh", fake_create)
    built = m.make_heat_mesh((2, 4))
    assert calls["shape"] == (2, 4)
    assert list(built.devices.flat) == perm
    assert built.axis_names == ("x", "y")


def test_mesh_partial_and_explicit_device_order():
    # Partial meshes (fewer devices than available) and explicit device
    # lists keep enumeration/user order — no topology reorder to rely on.
    import jax
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh

    devs = jax.devices()
    built = make_heat_mesh((2, 2))
    assert list(built.devices.flat) == devs[:4]
    pick = [devs[3], devs[1], devs[0], devs[2]]
    built = make_heat_mesh((2, 2), devices=pick)
    assert list(built.devices.flat) == pick


def test_mesh_topology_fallback_on_unfactorable(monkeypatch):
    # create_device_mesh refusals (unfactorable shape/topology) fall
    # back to enumeration order instead of erroring.
    import jax
    from jax.experimental import mesh_utils
    from parallel_heat_tpu.parallel import mesh as m

    def refuse(shape, devices=None):
        raise ValueError("cannot factor topology")

    monkeypatch.setattr(m, "_use_topology_order", lambda avail: True)
    monkeypatch.setattr(mesh_utils, "create_device_mesh", refuse)
    built = m.make_heat_mesh((2, 4))
    assert list(built.devices.flat) == jax.devices()
