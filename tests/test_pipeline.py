"""Asynchronous stream pipeline: pipelined `solve_stream` dispatch,
the background telemetry writer, the async checkpointer, and the
pipeline section of tools/metrics_report.py — all under the
dispatch-order-only contract (SEMANTICS.md "Pipelined stream"):
pipelining changes WHEN the host observes, never WHAT ran — grids,
observations, compiled programs, and checkpoint bytes are identical
to the synchronous loop."""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from parallel_heat_tpu import (
    HeatConfig,
    SupervisorPolicy,
    Telemetry,
    run_supervised,
    solve,
    solve_stream,
)
from parallel_heat_tpu.utils.checkpoint import (
    AsyncCheckpointer,
    generation_paths,
    load_checkpoint,
    save_generation,
)

_ROOT = os.path.join(os.path.dirname(__file__), "..")
_BASE = dict(nx=16, ny=16, backend="jnp")


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- pipelined stream: dispatch-order-only contract -------------------------

def test_pipelined_stream_bitwise_matches_sync_per_chunk():
    cfg = HeatConfig(steps=50, **_BASE)
    # The depth-1 contract: consume each grid BEFORE advancing (the
    # next chunk donates it). Depth 2 yields protected copies, so the
    # results can be held and compared afterwards.
    sync_rs = [(r.steps_run, r.to_numpy())
               for r in solve_stream(cfg, chunk_steps=10,
                                     pipeline_depth=1)]
    pipe_rs = list(solve_stream(cfg, chunk_steps=10, pipeline_depth=2))
    assert [r.steps_run for r in pipe_rs] == \
        [s for s, _ in sync_rs] == [10, 20, 30, 40, 50]
    for (_, a), b in zip(sync_rs, pipe_rs):
        np.testing.assert_array_equal(a, b.to_numpy())


def test_pipelined_shares_compiled_programs():
    # The acceptance contract: zero new _build_runner misses — every
    # depth runs the same compiled-program family (pipeline_depth is
    # stripped from cache keys like the guard).
    from parallel_heat_tpu import solver

    cfg = HeatConfig(steps=30, **_BASE)
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=10,
                                                pipeline_depth=1)]
    misses = solver._build_runner.cache_info().misses
    piped = [r.to_numpy()
             for r in solve_stream(cfg.replace(pipeline_depth=3),
                                   chunk_steps=10)]
    assert solver._build_runner.cache_info().misses == misses
    for a, b in zip(plain, piped):
        np.testing.assert_array_equal(a, b)


def test_pipelined_yielded_grids_survive_advancing():
    # At depth >= 2 every yielded grid is a donation-protected copy:
    # consuming it AFTER the generator advanced (the depth-1 contract
    # forbids this) still reads the correct boundary values.
    cfg = HeatConfig(steps=40, **_BASE)
    held = list(solve_stream(cfg, chunk_steps=10, pipeline_depth=2))
    sync = [r.to_numpy()
            for r in solve_stream(cfg, chunk_steps=10, pipeline_depth=1)]
    for r, want in zip(held, sync):
        np.testing.assert_array_equal(r.to_numpy(), want)


def test_pipelined_guard_and_diag_match_sync():
    cfg = HeatConfig(steps=60, guard_interval=20, diag_interval=20,
                     **_BASE)
    sync_rs = list(solve_stream(cfg, chunk_steps=10, pipeline_depth=1))
    pipe_rs = list(solve_stream(cfg, chunk_steps=10, pipeline_depth=2))
    assert [r.finite for r in pipe_rs] == [r.finite for r in sync_rs] \
        == [None, True, None, True, None, True]
    for a, b in zip(sync_rs, pipe_rs):
        if a.diagnostics is None:
            assert b.diagnostics is None
            continue
        # Same fused reduction over bitwise-identical grids -> the
        # observed values must be exactly equal, field by field.
        assert a.diagnostics == b.diagnostics


def test_pipelined_guard_detects_blowup():
    cfg = HeatConfig(steps=60, cx=5.0, cy=5.0, guard_interval=10,
                     pipeline_depth=2, **_BASE)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        flags = [(r.steps_run, r.finite)
                 for r in solve_stream(cfg, chunk_steps=10)]
    assert all(f is not None for _, f in flags)
    assert any(f is False for _, f in flags)
    assert any("runtime guard" in str(x.message) for x in w)


def test_resolved_pipeline_depth_auto():
    from parallel_heat_tpu.solver import resolved_pipeline_depth

    fixed = HeatConfig(steps=10, **_BASE)
    conv = HeatConfig(steps=10, converge=True, **_BASE)
    # The CPU test backend has no idle device for dispatch-ahead to
    # keep busy: auto resolves to 1 (2 on tpu/gpu fixed-step runs).
    assert resolved_pipeline_depth(fixed) == 1
    assert resolved_pipeline_depth(conv) == 1
    # explicit values win, argument over config field
    assert resolved_pipeline_depth(fixed, 3) == 3
    assert resolved_pipeline_depth(fixed.replace(pipeline_depth=2)) == 2


def test_pipeline_depth_validation():
    with pytest.raises(ValueError, match="pipeline_depth"):
        HeatConfig(pipeline_depth=0, **_BASE).validate()
    with pytest.raises(ValueError, match="fixed-step only"):
        HeatConfig(converge=True, pipeline_depth=2, **_BASE).validate()
    with pytest.raises(ValueError, match="fixed-step only"):
        next(solve_stream(HeatConfig(steps=40, converge=True, **_BASE),
                          chunk_steps=20, pipeline_depth=2))
    # converge mode auto-resolves to depth 1 and still converges
    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, backend="jnp")
    results = list(solve_stream(cfg, chunk_steps=500))
    assert results[-1].converged


@pytest.mark.slow
def test_pipelined_f32chunk_matches_one_shot():
    # Stream boundaries stay K-aligned rounding points under f32chunk
    # regardless of depth (SEMANTICS.md) — the pipelined stream must be
    # bitwise the one-shot run, like the sync stream is.
    # slow (tier-1 wall budget, round 15): the composition of two
    # contracts each pinned separately in tier-1 (pipelined == sync
    # bitwise; f32chunk stream-boundary alignment vs solve).
    kw = dict(nx=16, ny=128, steps=80, backend="jnp",
              dtype="bfloat16", accumulate="f32chunk")
    direct = solve(HeatConfig(**kw))
    last = None
    for last in solve_stream(HeatConfig(**kw), chunk_steps=32,
                             pipeline_depth=2):
        pass
    assert last.steps_run == 80
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_pipelined_sharded_stream_matches_sync():
    kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=40, **kw)
    sync_rs = [r.to_numpy()
               for r in solve_stream(cfg, chunk_steps=10,
                                     pipeline_depth=1)]
    pipe_rs = [r.to_numpy()
               for r in solve_stream(cfg, chunk_steps=10,
                                     pipeline_depth=2)]
    for a, b in zip(sync_rs, pipe_rs):
        np.testing.assert_array_equal(a, b)


def test_explain_reports_pipeline():
    from parallel_heat_tpu.solver import explain

    out = explain(HeatConfig(steps=10, pipeline_depth=2, **_BASE))
    assert "depth 2" in out["pipeline"]
    assert "pipeline" not in explain(HeatConfig(steps=10, **_BASE))


def test_pipelined_chunk_events_carry_timing_fields(tmp_path):
    p = tmp_path / "pipe.jsonl"
    cfg = HeatConfig(steps=30, **_BASE)
    with Telemetry(p) as tel:
        for _ in solve_stream(cfg, chunk_steps=10, telemetry=tel,
                              pipeline_depth=2):
            pass
    ev = _events(p)
    assert ev[0]["event"] == "run_header"
    assert ev[0]["pipeline_depth"] == 2
    chunks = [e for e in ev if e["event"] == "chunk"]
    assert len(chunks) == 3
    for c in chunks:
        # gap_s is the measured device-starvation lower bound (zero
        # when the pipeline stayed fed, positive when every dispatched
        # chunk finished while the host was still processing)
        assert c["gap_s"] >= 0.0
        assert c["dispatch_s"] >= 0
        assert c["drain_wait_s"] >= 0
        assert c["observe_s"] >= 0
    # the sync loop reports its idle gap + observer cost instead
    q = tmp_path / "sync.jsonl"
    with Telemetry(q) as tel:
        for _ in solve_stream(cfg, chunk_steps=10, telemetry=tel,
                              pipeline_depth=1):
            pass
    sync_chunks = [e for e in _events(q) if e["event"] == "chunk"]
    assert all("drain_wait_s" not in c for c in sync_chunks)
    assert all(c["gap_s"] >= 0 and c["observe_s"] >= 0
               for c in sync_chunks)


# -- async telemetry writer --------------------------------------------------

def test_async_writer_preserves_order_and_drains_on_close(tmp_path):
    p = tmp_path / "a.jsonl"
    with Telemetry(p, async_io=True) as tel:
        for i in range(50):
            tel.emit("chunk", step=i)
        tel.run_end(outcome="complete")
    ev = _events(p)
    assert [e["step"] for e in ev if e["event"] == "chunk"] \
        == list(range(50))
    assert ev[-1]["event"] == "run_end"


def test_async_writer_matches_sync_stream_content(tmp_path):
    cfg = HeatConfig(steps=30, **_BASE)
    a, b = tmp_path / "sync.jsonl", tmp_path / "async.jsonl"
    with Telemetry(a) as tel:
        for _ in solve_stream(cfg, chunk_steps=10, telemetry=tel,
                              pipeline_depth=1):
            pass
    with Telemetry(b, async_io=True) as tel:
        for _ in solve_stream(cfg, chunk_steps=10, telemetry=tel,
                              pipeline_depth=1):
            pass
    ka = [(e["event"], e.get("step")) for e in _events(a)]
    kb = [(e["event"], e.get("step")) for e in _events(b)]
    assert ka == kb


def test_async_writer_failure_warns_once_and_goes_quiet(tmp_path):
    tel = Telemetry(tmp_path / "a.jsonl", async_io=True)
    tel._f.close()  # yank the stream out from under the writer thread
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for i in range(5):
            tel.emit("chunk", step=i)
        tel.close()  # joins the writer: the warning landed by now
    assert sum("telemetry sink" in str(x.message) for x in w) == 1
    tel.emit("chunk", step=99)  # dead sink: silent no-op


# -- heartbeat throttle ------------------------------------------------------

def test_heartbeat_throttled_by_min_interval(tmp_path):
    hb = tmp_path / "hb.json"
    with Telemetry(tmp_path / "m.jsonl", heartbeat=hb,
                   heartbeat_interval_s=3600.0) as tel:
        tel.emit("chunk", step=1)
        first = json.load(open(hb))
        assert first["events"] == 1
        assert first["interval_s"] == 3600.0
        tel.emit("chunk", step=2)
        tel.emit("chunk", step=3)
        # throttled: the file still shows the first write
        assert json.load(open(hb))["events"] == 1
        # terminal events force a rewrite through the throttle
        tel.emit("run_end", outcome="complete")
        forced = json.load(open(hb))
        assert forced["events"] == 4
        assert forced["last_event"] == "run_end"
        tel.emit("chunk", step=4)
    # close() publishes the final state regardless of the interval
    final = json.load(open(hb))
    assert final["events"] == 5 and final["last_step"] == 4


# -- async checkpointer ------------------------------------------------------

def test_async_checkpointer_commits_in_order_and_matches_sync(tmp_path):
    cfg = HeatConfig(steps=30, **_BASE)
    grids = {r.steps_run: r.grid
             for r in solve_stream(cfg, chunk_steps=10,
                                   pipeline_depth=2)}
    sync_stem = tmp_path / "sync_ck"
    for step, g in grids.items():
        save_generation(sync_stem, g, step, cfg, keep=3)
    saver = AsyncCheckpointer(keep=3)
    try:
        for step, g in grids.items():
            saver.submit(tmp_path / "async_ck", g, step, cfg)
        saver.drain()
    finally:
        saver.close()
    sync_gens = generation_paths(sync_stem)
    async_gens = generation_paths(tmp_path / "async_ck")
    assert [s for s, _ in async_gens] == [s for s, _ in sync_gens] \
        == [10, 20, 30]
    for (_, sp), (_, ap) in zip(sync_gens, async_gens):
        gs, ss, _ = load_checkpoint(sp)
        ga, sa, _ = load_checkpoint(ap)
        assert ss == sa
        np.testing.assert_array_equal(np.asarray(gs), np.asarray(ga))
    assert all(r["path"] and not r["skipped"] for r in saver.records)


def test_async_checkpointer_skips_non_finite_snapshot(tmp_path):
    import jax.numpy as jnp

    cfg = HeatConfig(steps=10, **_BASE)
    good = jnp.ones((16, 16), jnp.float32)
    bad = good.at[3, 3].set(jnp.nan)
    saver = AsyncCheckpointer(keep=3)
    try:
        saver.submit(tmp_path / "ck", good, 10, cfg)
        saver.submit(tmp_path / "ck", bad, 20, cfg)
        saver.drain()
    finally:
        saver.close()
    # the commit gate held: the bad generation never landed, the good
    # one stays newest — rollback targets remain verified-good
    assert [s for s, _ in generation_paths(tmp_path / "ck")] == [10]
    recs = saver.records
    assert recs[0]["skipped"] is False and recs[1]["skipped"] is True


def test_async_checkpointer_surfaces_worker_error_at_drain(tmp_path):
    import jax.numpy as jnp

    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    cfg = HeatConfig(steps=10, **_BASE)
    saver = AsyncCheckpointer(keep=3)
    try:
        # stem under a FILE: the worker's save must fail, and the
        # failure must surface at the barrier — the same place a
        # synchronous save would have raised
        saver.submit(blocker / "sub" / "ck",
                     jnp.ones((16, 16), jnp.float32), 10, cfg)
        with pytest.raises(OSError):
            saver.drain()
    finally:
        saver.close()


# -- supervisor integration --------------------------------------------------

def test_supervisor_async_saves_match_sync_generations(tmp_path):
    cfg = HeatConfig(steps=60, **_BASE)
    kw = dict(checkpoint_every=20, guard_interval=10, backoff_base_s=0.0)
    s_sync = run_supervised(
        cfg, tmp_path / "sync",
        policy=SupervisorPolicy(async_checkpoint=False, **kw))
    s_async = run_supervised(
        cfg, tmp_path / "async",
        policy=SupervisorPolicy(async_checkpoint=True, **kw))
    assert s_async.checkpoints_written == s_sync.checkpoints_written
    sg = generation_paths(tmp_path / "sync")
    ag = generation_paths(tmp_path / "async")
    assert [s for s, _ in ag] == [s for s, _ in sg] == [20, 40, 60]
    for (_, sp), (_, ap) in zip(sg, ag):
        gs, ss, _ = load_checkpoint(sp)
        ga, sa, _ = load_checkpoint(ap)
        assert ss == sa
        np.testing.assert_array_equal(np.asarray(gs), np.asarray(ga))
    np.testing.assert_array_equal(s_async.result.to_numpy(),
                                  s_sync.result.to_numpy())


def test_supervisor_async_final_state_drained_before_return(tmp_path):
    # A throttled saver holds every commit open ~50 ms: the completion
    # barrier must still deliver all generations (and accurate counts)
    # by the time run_supervised returns.
    saver = AsyncCheckpointer(keep=3, throttle_s=0.05)
    try:
        sres = run_supervised(
            HeatConfig(steps=60, **_BASE), tmp_path / "ck",
            policy=SupervisorPolicy(checkpoint_every=20,
                                    backoff_base_s=0.0),
            checkpointer=saver)
    finally:
        saver.close()
    assert sres.checkpoints_written == 4  # gen 0 + 20/40/60
    assert [s for s, _ in generation_paths(tmp_path / "ck")] \
        == [20, 40, 60]
    assert str(sres.last_checkpoint).endswith(
        ".g000000000060.npz")


def test_stall_verdict_not_masked_by_failed_async_save(tmp_path):
    # A worker error pending at the stall classifier's barrier must not
    # replace the PermanentFailure(kind="stalled") verdict: both the
    # stall-path barrier and fail()'s barrier swallow saver errors so
    # the diagnosis (and the run_end telemetry) still land.
    from parallel_heat_tpu import PermanentFailure

    class _ExplodingSaver(AsyncCheckpointer):
        def drain(self):
            super().drain()
            raise OSError("disk full (injected)")

    u0 = np.zeros((16, 16), np.float32)
    u0[0, :] = 1000.0
    cfg = HeatConfig(steps=3500, converge=True, check_interval=10,
                     eps=1e-6, **_BASE)
    saver = _ExplodingSaver(keep=3)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with pytest.raises(PermanentFailure) as ei:
                run_supervised(
                    cfg, tmp_path / "ck",
                    policy=SupervisorPolicy(checkpoint_every=500,
                                            guard_interval=250,
                                            stall_windows=3,
                                            backoff_base_s=0.0),
                    initial=u0, checkpointer=saver)
    finally:
        saver.close()
    assert ei.value.kind == "stalled"
    assert "residual stalled" in ei.value.diagnosis


def test_stall_emits_single_failure_barrier(tmp_path):
    # One logical drain -> one checkpoint_barrier event: the stall path
    # drains before building its diagnosis and fail() must not drain
    # (and emit) a second time.
    from parallel_heat_tpu import PermanentFailure

    u0 = np.zeros((16, 16), np.float32)
    u0[0, :] = 1000.0
    cfg = HeatConfig(steps=3500, converge=True, check_interval=10,
                     eps=1e-6, **_BASE)
    m = tmp_path / "m.jsonl"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with Telemetry(m) as tel:
            with pytest.raises(PermanentFailure):
                run_supervised(
                    cfg, tmp_path / "ck",
                    policy=SupervisorPolicy(checkpoint_every=500,
                                            guard_interval=250,
                                            stall_windows=3,
                                            backoff_base_s=0.0),
                    initial=u0, telemetry=tel)
    barriers = [e for e in _events(m)
                if e["event"] == "checkpoint_barrier"]
    assert [b["reason"] for b in barriers] == ["failure"]


def test_cli_pipeline_depth_flag(tmp_path, capsys):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    assert main(["--nx", "16", "--ny", "16", "--steps", "10",
                 "--pipeline-depth", "bogus"]) == 2
    assert "--pipeline-depth" in capsys.readouterr().err
    assert main(["--nx", "16", "--ny", "16", "--steps", "10",
                 "--converge", "--pipeline-depth", "2"]) == 2
    assert "fixed-step only" in capsys.readouterr().err
    out1, out2 = tmp_path / "d1.dat", tmp_path / "d2.dat"
    for depth, out in (("1", out1), ("2", out2)):
        assert main(["--nx", "16", "--ny", "16", "--steps", "40",
                     "--backend", "jnp", "--checkpoint",
                     str(tmp_path / f"ck{depth}"),
                     "--checkpoint-every", "10",
                     "--pipeline-depth", depth,
                     "--out", str(out), "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out1), read_dat(out2))


def test_resume_command_carries_pipeline_depth(tmp_path):
    from parallel_heat_tpu.supervisor import _resume_command
    from parallel_heat_tpu.utils.checkpoint import checkpoint_stem

    cfg = HeatConfig(steps=100, pipeline_depth=2, **_BASE)
    policy = SupervisorPolicy(async_checkpoint=False).validate()
    cmd = _resume_command(cfg, checkpoint_stem(tmp_path / "ck"), 100,
                          policy)
    assert "--pipeline-depth 2" in cmd
    assert "--no-async-checkpoint" in cmd


# -- metrics_report pipeline section -----------------------------------------

def _report(args):
    return subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "tools", "metrics_report.py")] + args,
        capture_output=True, text=True, timeout=120)


def test_metrics_report_pipeline_section(tmp_path):
    # explicit depth 2: auto resolves to 1 on the CPU test backend
    cfg = HeatConfig(steps=60, pipeline_depth=2, **_BASE)
    m = tmp_path / "m.jsonl"
    saver = AsyncCheckpointer(keep=3, throttle_s=0.02)
    try:
        with Telemetry(m, async_io=True) as tel:
            sres = run_supervised(
                cfg, tmp_path / "ck",
                policy=SupervisorPolicy(checkpoint_every=20,
                                        guard_interval=10,
                                        backoff_base_s=0.0),
                telemetry=tel, checkpointer=saver)
    finally:
        saver.close()
    assert sres.steps_done == 60
    rep = _report([str(m), "--json"])
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    pl = doc["pipeline"]
    assert pl["mode"] == "pipelined"
    assert 0 < pl["device_busy_frac"] <= 1
    assert pl["observer_drain_s"]["p90"] >= 0
    assert pl["device_wait_s"]["p90"] >= 0
    ck = doc["checkpoints"]
    assert ck["async_saves"] == ck["saves"] == 4
    assert ck["async_overlap_share"] is not None
    # a throttled saver makes the final barrier wait measurable
    assert ck["barrier_wait_s"] > 0
    # busy threshold drives the exit code: an impossible floor fails
    bad = _report([str(m), "--fail-on", "busy<1.01"])
    assert bad.returncode == 2 and "ANOMALY" in bad.stdout
    ok = _report([str(m), "--fail-on", "permanent_failure,busy<0.1"])
    assert ok.returncode == 0


def test_metrics_report_mixed_mode_stream(tmp_path):
    # A multi-segment stream can mix modes (a pipelined run resumed at
    # depth 1): each chunk must contribute under its own bracket
    # semantics — pipelined walls CONTAIN their gap, sync walls don't.
    m = tmp_path / "mixed.jsonl"
    lines = [json.dumps({"schema": 1, "event": "run_header",
                         "t_wall": 1.0, "t_mono": 1.0,
                         "config": {"nx": 16, "ny": 16, "steps": 40}})]
    for i in range(2):  # pipelined segment: busy 1.0 of 1.0 each
        lines.append(json.dumps({
            "schema": 1, "event": "chunk", "t_wall": 2.0 + i,
            "t_mono": 2.0 + i, "step": 10 * (i + 1), "steps": 10,
            "wall_s": 1.0, "gap_s": 0.0, "dispatch_s": 0.001,
            "drain_wait_s": 0.9, "observe_s": 0.01}))
    for i in range(2):  # sync segment: busy 1.0 of 2.0 each
        lines.append(json.dumps({
            "schema": 1, "event": "chunk", "t_wall": 4.0 + i,
            "t_mono": 4.0 + i, "step": 30 + 10 * i, "steps": 10,
            "wall_s": 1.0, "gap_s": 1.0, "observe_s": 0.5}))
    m.write_text("\n".join(lines) + "\n")
    rep = _report([str(m), "--json"])
    assert rep.returncode == 0, rep.stderr[-2000:]
    pl = json.loads(rep.stdout)["pipeline"]
    assert pl["mode"] == "mixed"
    # (1 + 1 + 1 + 1) busy over (1 + 1 + 2 + 2) available
    assert pl["device_busy_frac"] == pytest.approx(4 / 6)


def test_metrics_report_busy_threshold_without_timing_fields(tmp_path):
    # A pre-pipeline stream has no gap/drain fields: asking for a busy
    # floor on it must be an anomaly, not a silent pass.
    m = tmp_path / "old.jsonl"
    lines = [json.dumps({"schema": 1, "event": "run_header",
                         "t_wall": 1.0, "t_mono": 1.0,
                         "config": {"nx": 16, "ny": 16, "steps": 10}}),
             json.dumps({"schema": 1, "event": "chunk", "t_wall": 2.0,
                         "t_mono": 2.0, "step": 10, "steps": 10,
                         "wall_s": 0.01})]
    m.write_text("\n".join(lines) + "\n")
    rep = _report([str(m), "--fail-on", "busy<0.5"])
    assert rep.returncode == 2
    assert "no per-chunk timing fields" in rep.stdout
