import numpy as np

from parallel_heat_tpu.utils.io import read_dat, write_dat, _format_dat_python


def test_format_matches_handwritten_golden(tmp_path):
    # u[ix, iy]; prtdat prints iy=ny-1..0 per line, ix ascending within it,
    # C "%6.1f" with single-space separators (mpi/...stat.c:326-341).
    u = np.array(
        [[0.0, 1.5, 2.25],
         [10.0, -3.0, 100.0],
         [1234.56, 7.0, -0.04]],
        dtype=np.float32,
    )  # (nx=3, ny=3)
    golden = (
        "   2.2  100.0   -0.0\n"
        "   1.5   -3.0    7.0\n"
        "   0.0   10.0 1234.6\n"
    )
    p = tmp_path / "g.dat"
    write_dat(p, u, use_native=False)
    assert p.read_text() == golden


def test_wide_values_overflow_width_like_c(tmp_path):
    # C %6.1f is a *minimum* width: big values take more columns.
    u = np.array([[1234567.0, 2.0]], dtype=np.float32)  # nx=1, ny=2
    p = tmp_path / "w.dat"
    write_dat(p, u, use_native=False)
    assert p.read_text() == "   2.0\n1234567.0\n"


def test_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    u = (rng.standard_normal((17, 11)) * 100).astype(np.float32)
    p = tmp_path / "r.dat"
    write_dat(p, u, use_native=False)
    back = read_dat(p)
    np.testing.assert_allclose(back, u, atol=0.05)  # %.1f quantization


def test_python_formatter_is_c_compatible():
    # Cross-check the formatter against printf semantics via ctypes libc.
    import ctypes

    libc = ctypes.CDLL(None)
    buf = ctypes.create_string_buffer(64)
    vals = [0.0, -0.05, 3.14159, 99999.99, -1234.5, 2.5, 3.5]
    for v in vals:
        libc.snprintf(buf, 64, b"%6.1f", ctypes.c_double(v))
        assert buf.value.decode() == f"{v:6.1f}", v
