"""NumPy reference implementations — the semantics oracle for all tests.

Independent of the package's JAX code paths: plain NumPy loops/slices in
float64, written straight from the update rule in the reference
(``cuda/cuda_heat.cu:57-65``).
"""

import numpy as np


def init_grid(nx, ny, dtype=np.float64):
    u = np.empty((nx, ny), dtype=np.float64)
    for ix in range(nx):
        for iy in range(ny):
            u[ix, iy] = ix * (nx - ix - 1) * iy * (ny - iy - 1)
    return u.astype(dtype)


def step(u, cx=0.1, cy=0.1):
    """One Jacobi step, interior only (float64)."""
    u = u.astype(np.float64)
    v = u.copy()
    c = u[1:-1, 1:-1]
    v[1:-1, 1:-1] = (
        c
        + cx * (u[2:, 1:-1] + u[:-2, 1:-1] - 2.0 * c)
        + cy * (u[1:-1, 2:] + u[1:-1, :-2] - 2.0 * c)
    )
    return v


def run(u, steps, cx=0.1, cy=0.1):
    for _ in range(steps):
        u = step(u, cx, cy)
    return u


def run_converge(u, max_steps, check_interval, eps, cx=0.1, cy=0.1):
    """Chunked convergence semantics matching the package's definition."""
    k = 0
    n_full = max_steps // check_interval
    for _ in range(n_full):
        prev = u
        for _ in range(check_interval):
            prev = u
            u = step(u, cx, cy)
        k += check_interval
        res = np.max(np.abs(u - prev))
        if res < eps:
            return u, k, True, res
    rem = max_steps % check_interval
    for _ in range(rem):
        u = step(u, cx, cy)
    k += rem
    return u, k, False, np.inf if n_full == 0 else res


def step3d(u, cx=0.1, cy=0.1, cz=0.1):
    u = u.astype(np.float64)
    v = u.copy()
    c = u[1:-1, 1:-1, 1:-1]
    v[1:-1, 1:-1, 1:-1] = (
        c
        + cx * (u[2:, 1:-1, 1:-1] + u[:-2, 1:-1, 1:-1] - 2.0 * c)
        + cy * (u[1:-1, 2:, 1:-1] + u[1:-1, :-2, 1:-1] - 2.0 * c)
        + cz * (u[1:-1, 1:-1, 2:] + u[1:-1, 1:-1, :-2] - 2.0 * c)
    )
    return v
