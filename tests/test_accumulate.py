"""The f32-chunk accumulation semantics (``accumulate="f32chunk"``).

SEMANTICS.md's sub-f32 rounding-points contract: chunks of K = sublane
steps carry float32 and round to storage once per chunk. The reference
never resolved this choice — its MPI and CUDA variants silently
disagree about promotion (`mpi/...stat.c:171-174` double literals vs
`cuda/cuda_heat.cu:62` ``2.0f``, SURVEY.md §2d.7); here it is an
explicit, priced, tested flag. The Pallas acc kernels (E and I) are
checked against the chunked-f32 jnp multistep, which is itself checked
bitwise against a hand-rolled chunk loop.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.ops import pallas_stencil as ps
from parallel_heat_tpu.ops.stencil import step_2d
from parallel_heat_tpu.solver import explain, make_initial_grid


def _oracle_f32chunk(u0, n, K, cx=0.1, cy=0.1):
    """Hand-rolled chunked-f32 reference: K-step f32 chunks, one
    storage rounding per chunk (the SEMANTICS.md contract stated as
    the simplest possible loop)."""
    v = jnp.asarray(u0)
    while n > 0:
        kk = min(K, n)
        w = v.astype(jnp.float32)
        for _ in range(kk):
            w = step_2d(w, cx, cy)
        v = w.astype(v.dtype)
        n -= kk
    return np.asarray(v).astype("f8")


# --- validation -----------------------------------------------------------

def test_validate_rejects_bad_accumulate():
    with pytest.raises(ValueError, match="storage.*f32chunk"):
        HeatConfig(nx=16, ny=16, accumulate="f64always").validate()


def test_validate_rejects_f32_storage():
    with pytest.raises(ValueError, match="sub-f32"):
        HeatConfig(nx=16, ny=16, accumulate="f32chunk").validate()


def test_validate_rejects_3d():
    with pytest.raises(ValueError, match="2D"):
        HeatConfig(nx=16, ny=16, nz=16, dtype="bfloat16",
                   accumulate="f32chunk").validate()


def test_validate_rejects_mesh():
    with pytest.raises(ValueError, match="single-device"):
        HeatConfig(nx=32, ny=32, dtype="bfloat16", mesh_shape=(2, 2),
                   accumulate="f32chunk").validate()


# --- explain / decision site ---------------------------------------------

def test_explain_reports_f32chunk_paths():
    p = explain(HeatConfig(nx=64, ny=256, steps=10, dtype="bfloat16",
                           backend="pallas",
                           accumulate="f32chunk"))["path"]
    assert "f32-chunk accumulation" in p
    pj = explain(HeatConfig(nx=64, ny=256, steps=10, dtype="bfloat16",
                            backend="jnp", accumulate="f32chunk"))["path"]
    assert "chunked-f32 jnp" in pj


def test_pick_never_chooses_single_step_kernels():
    # Single-step kernels (A/B/C) round every step and cannot honor the
    # contract; the acc decision site only returns E, I, or jnp.
    for shape in ((32, 128), (64, 256), (128, 1024)):
        kind, _ = ps.pick_single_2d(shape, "bfloat16", 0.1, 0.1,
                                    accumulate="f32chunk")
        assert kind in ("E", "I", "jnp")


# --- semantics ------------------------------------------------------------

def test_jnp_f32chunk_matches_handrolled_oracle_bitwise():
    cfg = HeatConfig(nx=64, ny=256, steps=37, dtype="bfloat16",
                     backend="jnp", accumulate="f32chunk")
    got = solve(cfg).to_numpy().astype("f8")
    ref = _oracle_f32chunk(make_initial_grid(cfg), 37, 16)
    np.testing.assert_array_equal(got, ref)


def test_kernel_e_acc_matches_contract():
    # Kernel E's acc variant rounds at the same points as the jnp
    # chunked path; the factored-vs-textbook f32 forms differ only at
    # chunk-boundary roundings — storage-dtype-ulp agreement
    # (SEMANTICS.md cross-path contract).
    cfg = HeatConfig(nx=64, ny=256, steps=37, dtype="bfloat16",
                     backend="pallas", accumulate="f32chunk")
    assert "kernel E" in explain(cfg)["path"]
    got = solve(cfg).to_numpy().astype("f8")
    ref = _oracle_f32chunk(make_initial_grid(cfg), 37, 16)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, rtol=8e-3, atol=0)


def test_kernel_i_acc_matches_contract():
    u0 = jnp.asarray(make_initial_grid(
        HeatConfig(nx=64, ny=128, steps=1, dtype="bfloat16")))
    ms = ps._tile_temporal_multistep((64, 128), "bfloat16", 0.1, 0.1,
                                     acc_f32=True)
    assert ms is not None
    got = np.asarray(ms[0](u0, 37)).astype("f8")
    ref = _oracle_f32chunk(u0, 37, 16)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, rtol=8e-3, atol=0)


def test_remainder_chunk_rounds_after_r_steps():
    # steps=17 = one full 16-chunk + a 1-step remainder chunk; the
    # remainder rounds after 1 step (SEMANTICS.md). The hand-rolled
    # oracle encodes exactly that.
    cfg = HeatConfig(nx=64, ny=256, steps=17, dtype="bfloat16",
                     backend="jnp", accumulate="f32chunk")
    got = solve(cfg).to_numpy().astype("f8")
    ref = _oracle_f32chunk(make_initial_grid(cfg), 17, 16)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.slow
def test_f32chunk_reduces_drift_vs_f64_oracle():
    # The point of the flag: fewer rounding events -> lower accumulated
    # drift against the float64 oracle.
    # slow (tier-1 wall budget, round 15): re-proves the measured
    # drift ordering the committed acc_ab_r5.json artifact and the
    # HL104 rounding-chain proof already pin; the bitwise f32chunk
    # contracts stay in tier-1.
    from tests.oracle import init_grid, run

    nx, ny, steps = 64, 256, 320
    ref64 = run(init_grid(nx, ny), steps)
    kw = dict(nx=nx, ny=ny, steps=steps, dtype="bfloat16",
              backend="jnp")
    d_storage = np.abs(
        solve(HeatConfig(**kw)).to_numpy().astype("f8") - ref64).max()
    d_chunk = np.abs(
        solve(HeatConfig(accumulate="f32chunk", **kw))
        .to_numpy().astype("f8") - ref64).max()
    assert d_chunk < d_storage


def test_f32chunk_converge_mode():
    # The residual is the last step's pre-rounding f32 update; converge
    # mode must run and stop like the storage path does.
    # Small grid: the residual decays ~5%/window here, so ulp-level
    # cross-path differences shift the eps-crossing by at most a few
    # check windows.
    kw = dict(nx=20, ny=128, steps=6000, converge=True, eps=1.0,
              check_interval=16, dtype="bfloat16")
    a = solve(HeatConfig(backend="jnp", accumulate="f32chunk", **kw))
    b = solve(HeatConfig(backend="pallas", accumulate="f32chunk", **kw))
    assert a.converged and b.converged
    assert abs(a.steps_run - b.steps_run) <= 3 * kw["check_interval"]


def test_solve_stream_f32chunk_matches_solve():
    # The chunked driver must compose with the acc semantics: chunk
    # boundaries land on multiples of chunk_steps (here a multiple of
    # K=16), so streaming doesn't move any rounding point.
    from parallel_heat_tpu.solver import solve_stream

    kw = dict(nx=64, ny=256, steps=96, dtype="bfloat16",
              backend="pallas", accumulate="f32chunk")
    whole = solve(HeatConfig(**kw)).to_numpy()
    last = None
    for res in solve_stream(HeatConfig(**kw), chunk_steps=32):
        last = res
    assert last is not None and last.steps_run == 96
    np.testing.assert_array_equal(last.to_numpy(), whole)


def test_solve_stream_f32chunk_misaligned_chunk_rounds_up():
    # Regression (round-5 advisor finding): a chunk_steps that is NOT
    # a multiple of K=16 used to restart the f32 chunk at every stream
    # boundary — silently shifting the rounding schedule away from the
    # unchunked run's. solve_stream now rounds chunk_steps up to the
    # sublane multiple (SEMANTICS.md contract), so the stream is
    # bitwise the one-shot run and each yield lands on the rounded
    # boundary.
    from parallel_heat_tpu.solver import solve_stream

    kw = dict(nx=64, ny=256, steps=96, dtype="bfloat16",
              backend="pallas", accumulate="f32chunk")
    whole = solve(HeatConfig(**kw)).to_numpy()
    seen = []
    last = None
    for res in solve_stream(HeatConfig(**kw), chunk_steps=10):
        seen.append(res.steps_run)
        last = res
    assert seen == [16, 32, 48, 64, 80, 96]  # rounded to K, not 10
    np.testing.assert_array_equal(last.to_numpy(), whole)
    # Converge mode needs no extra rounding: check_interval rounding
    # already reproduces the unchunked per-interval chunk restarts.
    kwc = dict(nx=32, ny=64, steps=64, dtype="bfloat16", converge=True,
               eps=1e-30, check_interval=4, backend="pallas",
               accumulate="f32chunk")
    wholec = solve(HeatConfig(**kwc)).to_numpy()
    lastc = None
    for res in solve_stream(HeatConfig(**kwc), chunk_steps=10):
        lastc = res
    np.testing.assert_array_equal(lastc.to_numpy(), wholec)


def test_boundary_exact_under_f32chunk():
    cfg = HeatConfig(nx=64, ny=256, steps=33, dtype="bfloat16",
                     backend="pallas", accumulate="f32chunk")
    u0 = np.asarray(make_initial_grid(cfg))
    got = solve(cfg).to_numpy()
    np.testing.assert_array_equal(got[0, :], u0[0, :])
    np.testing.assert_array_equal(got[-1, :], u0[-1, :])
    np.testing.assert_array_equal(got[:, 0], u0[:, 0])
    np.testing.assert_array_equal(got[:, -1], u0[:, -1])
