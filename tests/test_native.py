"""Native C++ I/O runtime vs the Python oracle implementations."""

import numpy as np
import pytest

from parallel_heat_tpu.models import HeatPlate2D
from parallel_heat_tpu.native import binding
from parallel_heat_tpu.utils.io import _format_dat_python, write_dat

needs_native = pytest.mark.skipif(
    not binding.available(), reason="native toolchain unavailable"
)


@needs_native
def test_native_writer_byte_identical_to_python(tmp_path):
    rng = np.random.default_rng(0)
    cases = [
        (rng.standard_normal((13, 7)) * 100).astype(np.float32),
        np.array([[1234567.0, -0.04, 2.25]], dtype=np.float32),
        HeatPlate2D(64, 64).init_grid_np(np.float32),
    ]
    for i, u in enumerate(cases):
        p = tmp_path / f"n{i}.dat"
        binding.write_dat(p, u)
        assert p.read_bytes() == _format_dat_python(u).encode()


@needs_native
def test_write_dat_prefers_native_and_matches(tmp_path):
    u = (np.random.default_rng(1).standard_normal((33, 17)) * 50).astype(
        np.float32
    )
    p1, p2 = tmp_path / "a.dat", tmp_path / "b.dat"
    write_dat(p1, u, use_native=True)
    write_dat(p2, u, use_native=False)
    assert p1.read_bytes() == p2.read_bytes()


@needs_native
def test_native_init_matches_model():
    got = binding.init_grid(100, 80)
    want = HeatPlate2D(100, 80).init_grid_np(np.float32)
    np.testing.assert_array_equal(got, want)


@needs_native
def test_native_writer_error_on_bad_path():
    u = np.zeros((3, 3), dtype=np.float32)
    with pytest.raises(OSError):
        binding.write_dat("/nonexistent-dir/x.dat", u)
