"""Native C++ I/O runtime vs the Python oracle implementations."""

import numpy as np
import pytest

from parallel_heat_tpu.models import HeatPlate2D
from parallel_heat_tpu.native import binding
from parallel_heat_tpu.utils.io import _format_dat_python, write_dat

needs_native = pytest.mark.skipif(
    not binding.available(), reason="native toolchain unavailable"
)


@needs_native
def test_native_writer_byte_identical_to_python(tmp_path):
    rng = np.random.default_rng(0)
    cases = [
        (rng.standard_normal((13, 7)) * 100).astype(np.float32),
        np.array([[1234567.0, -0.04, 2.25]], dtype=np.float32),
        HeatPlate2D(64, 64).init_grid_np(np.float32),
    ]
    for i, u in enumerate(cases):
        p = tmp_path / f"n{i}.dat"
        binding.write_dat(p, u)
        assert p.read_bytes() == _format_dat_python(u).encode()


@needs_native
def test_write_dat_prefers_native_and_matches(tmp_path):
    u = (np.random.default_rng(1).standard_normal((33, 17)) * 50).astype(
        np.float32
    )
    p1, p2 = tmp_path / "a.dat", tmp_path / "b.dat"
    write_dat(p1, u, use_native=True)
    write_dat(p2, u, use_native=False)
    assert p1.read_bytes() == p2.read_bytes()


@needs_native
def test_native_init_matches_model():
    got = binding.init_grid(100, 80)
    want = HeatPlate2D(100, 80).init_grid_np(np.float32)
    np.testing.assert_array_equal(got, want)


@needs_native
def test_native_writer_error_on_bad_path():
    u = np.zeros((3, 3), dtype=np.float32)
    with pytest.raises(OSError):
        binding.write_dat("/nonexistent-dir/x.dat", u)


@needs_native
def test_native_mt_writer_byte_identical(tmp_path):
    u = (np.random.default_rng(2).standard_normal((257, 129)) * 300).astype(
        np.float32
    )
    p1, p2 = tmp_path / "mt.dat", tmp_path / "st.dat"
    binding.write_dat(p1, u, threads=4)
    binding.write_dat(p2, u, threads=1)
    assert p1.read_bytes() == p2.read_bytes()
    assert p1.read_bytes() == _format_dat_python(u).encode()


@needs_native
def test_native_reader_roundtrip():
    from parallel_heat_tpu.utils.io import read_dat

    u = HeatPlate2D(41, 23).init_grid_np(np.float32)
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "rt.dat")
        write_dat(p, u)
        got_native = read_dat(p, use_native=True)
        got_python = read_dat(p, use_native=False)
    np.testing.assert_array_equal(got_native, got_python)
    # %6.1f quantizes to 0.1: compare against the rounded grid
    np.testing.assert_allclose(got_native, np.round(u, 1), atol=0.051)


@needs_native
def test_native_reader_error_on_missing_file():
    with pytest.raises(OSError):
        binding.read_dat("/nonexistent-dir/x.dat")


@needs_native
def test_native_reader_rejects_ragged_lines(tmp_path):
    p = tmp_path / "ragged.dat"
    p.write_text("   1.0    2.0\n   3.0\n")
    with pytest.raises(OSError):
        binding.read_dat(str(p))
