"""Fleet federation: lease-partitioned queue, cross-host takeover.

Everything here is fast and deterministic — FleetHosts step on
injected clocks with scripted worker handles, exactly like
``tests/test_service.py`` drives a single Heatd. The contract pinned
(SEMANTICS.md "Fleet durability"): the journal stays single-writer
per partition (lease link/rename commits decide the writer), a lost
host's in-flight jobs are adopted by exactly one peer with an audited
``host_lost``/``adopted`` lineage, and routing is a pure function of
the fleet's durable state. Real multi-process death lives in the
``fleet_*`` cells of ``tools/chaos_matrix.py`` and the one
``slow``-marked subprocess test at the bottom.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from parallel_heat_tpu.service import client, fleet
from parallel_heat_tpu.service.harness import inline_launcher
from parallel_heat_tpu.service.store import (
    JobSpec,
    JobStore,
    read_journal_file,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_T0 = 1000.0


# ---------------------------------------------------------------------------
# Test doubles (the test_service.py idiom)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=_T0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeHandle:
    def __init__(self, rc=None):
        self.rc = rc
        self.pid = os.getpid()
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


class ScriptedLauncher:
    def __init__(self):
        self.dispatches = []

    def __call__(self, job_id, worker_id, attempt, deadline_t):
        h = FakeHandle()
        self.dispatches.append(
            {"job_id": job_id, "worker_id": worker_id,
             "attempt": attempt, "deadline_t": deadline_t,
             "handle": h})
        return h

    def last(self, job_id):
        for d in reversed(self.dispatches):
            if d["job_id"] == job_id:
                return d
        raise KeyError(job_id)


def _fleet_root(tmp_path, partitions=2, lease_timeout_s=10.0):
    root = str(tmp_path / "fleet")
    fleet.fleet_init(root, partitions=partitions,
                     lease_timeout_s=lease_timeout_s,
                     clock=lambda: _T0)
    return root


def _host(root, name, clock, launcher=None, **kw):
    opts = dict(kw.pop("daemon_opts", {}))
    opts.setdefault("launcher", launcher or ScriptedLauncher())
    opts.setdefault("requeue_backoff_base_s", 0.0)
    cfg = fleet.FleetHostConfig(
        fleet_root=root, host=name, clock=clock,
        sleep_fn=lambda s: None, daemon_opts=opts, **kw)
    return fleet.FleetHost(cfg)


def _spec(job_id, nx=16, steps=60, **kw):
    return JobSpec(job_id=job_id,
                   config={"nx": nx, "ny": nx, "steps": steps,
                           "backend": "jnp"}, **kw)


def _finish(store, d, outcome, rc=0, **fields):
    doc = {"outcome": outcome, "worker": d["worker_id"],
           "attempt": d["attempt"], "job_id": d["job_id"]}
    doc.update(fields)
    store.write_result(d["job_id"], d["attempt"], doc)
    d["handle"].rc = rc


def _events(proot, job_id=None, event=None):
    evs, _bad, _torn = read_journal_file(
        os.path.join(proot, "journal.jsonl"))
    return [e for e in evs
            if (job_id is None or e.get("job_id") == job_id)
            and (event is None or e.get("event") == event)]


# ---------------------------------------------------------------------------
# Fleet root layout
# ---------------------------------------------------------------------------

def test_fleet_init_layout_and_grow_only(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    assert fleet.is_fleet_root(root)
    names = [n for n, _ in fleet.partition_roots(root)]
    assert names == ["p00", "p01"]
    for _, proot in fleet.partition_roots(root):
        assert os.path.isdir(os.path.join(proot, "spool"))
    assert os.path.isdir(os.path.join(root, "leases"))
    assert os.path.isdir(os.path.join(root, "hosts"))
    # Idempotent re-init can only GROW the partition count (jobs may
    # already live in the existing partitions).
    doc = fleet.fleet_init(root, partitions=1)
    assert doc["partitions"] == 2
    doc = fleet.fleet_init(root, partitions=3)
    assert doc["partitions"] == 3
    assert [n for n, _ in fleet.partition_roots(root)] \
        == ["p00", "p01", "p02"]
    # A plain queue root is NOT a fleet root: the tools keep their
    # single-daemon view.
    q = tmp_path / "plain"
    JobStore(q).close()
    assert not fleet.is_fleet_root(str(q))


def test_fleet_init_rejects_bad_knobs(tmp_path):
    with pytest.raises(ValueError):
        fleet.fleet_init(str(tmp_path / "f1"), partitions=0)
    with pytest.raises(ValueError):
        fleet.fleet_init(str(tmp_path / "f2"), lease_timeout_s=0.0)


# ---------------------------------------------------------------------------
# Lease protocol: link-committed claims, rename-committed takeovers
# ---------------------------------------------------------------------------

def test_claim_lease_exactly_one_winner(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    a = fleet.claim_lease(root, "p00", "hosta", epoch=1,
                          timeout_s=10.0, now=_T0)
    assert a is not None and a["host"] == "hosta" and a["epoch"] == 1
    # The link is the commit point: a second claimant loses loudly.
    assert fleet.claim_lease(root, "p00", "hostb", epoch=1,
                             timeout_s=10.0, now=_T0) is None
    assert fleet.read_lease(root, "p00")["host"] == "hosta"
    assert not fleet.lease_stale(a, _T0 + 9.9)
    assert fleet.lease_stale(a, _T0 + 10.1)


def test_steal_lease_exactly_one_winner_from_same_observation(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    fleet.claim_lease(root, "p00", "ghost", epoch=1,
                      timeout_s=1.0, now=_T0 - 60.0)
    observed = fleet.read_lease(root, "p00")
    assert fleet.lease_stale(observed, _T0)
    # Two peers judged the SAME stale lease: the rename commit lets
    # exactly one through (the loser gets ENOENT, never a duplicate).
    wins = [fleet.steal_lease(root, "p00", observed, h,
                              timeout_s=10.0, now=_T0)
            for h in ("hostb", "hostc")]
    winners = [w for w in wins if w is not None]
    assert len(winners) == 1
    assert winners[0]["epoch"] == 2
    assert fleet.read_lease(root, "p00")["host"] == winners[0]["host"]


def test_steal_rolls_back_when_holder_renewed_meanwhile(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    fleet.claim_lease(root, "p00", "hosta", epoch=1,
                      timeout_s=10.0, now=_T0 - 60.0)
    observed = fleet.read_lease(root, "p00")
    assert fleet.lease_stale(observed, _T0)
    # Between the staleness read and the rename, the "dead" holder
    # heartbeats: the thief must notice the fresher bytes, restore the
    # live lease, and walk away.
    renewed = fleet.renew_lease(root, "p00", "hosta", 1, now=_T0)
    assert renewed is not None
    assert fleet.steal_lease(root, "p00", observed, "hostb",
                             timeout_s=10.0, now=_T0) is None
    cur = fleet.read_lease(root, "p00")
    assert cur["host"] == "hosta" and cur["epoch"] == 1


def test_renew_lease_detects_loss(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    fleet.claim_lease(root, "p00", "hosta", epoch=1,
                      timeout_s=10.0, now=_T0)
    assert fleet.renew_lease(root, "p00", "hostb", 1,
                             now=_T0 + 1) is None  # not ours
    assert fleet.renew_lease(root, "p00", "hosta", 2,
                             now=_T0 + 1) is None  # wrong epoch
    doc = fleet.renew_lease(root, "p00", "hosta", 1, now=_T0 + 1)
    assert doc is not None and doc["t_wall"] == _T0 + 1
    assert fleet.release_lease(root, "p00", "hosta", 1)
    assert fleet.read_lease(root, "p00") is None
    # A renew after takeover/release = the lease is simply gone.
    assert fleet.renew_lease(root, "p00", "hosta", 1,
                             now=_T0 + 2) is None


def test_journal_lease_epoch_survives_release(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    proot = fleet.partition_root(root, "p00")
    assert fleet.journal_lease_epoch(proot) == 0
    store = JobStore(proot, create=False)
    store.journal.append("lease_claimed", partition="p00", epoch=1,
                         kind="claim", host="a")
    store.journal.append("host_lost", partition="p00", epoch=2,
                         lost_host="a")
    store.close()
    # The journal is the durable monotone record: a fresh claim after
    # a graceful release continues the chain from here.
    assert fleet.journal_lease_epoch(proot) == 2


# ---------------------------------------------------------------------------
# Cache-aware routing
# ---------------------------------------------------------------------------

def test_route_least_loaded_with_deterministic_ties(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    cfg = {"nx": 16, "ny": 16, "steps": 60, "backend": "jnp"}
    d = fleet.route_submission(root, cfg, now=_T0)
    assert d["kind"] == "load" and d["partition"] == "p00"
    assert d["host"] is None  # unleased: work stealing picks it up
    # One spooled job on p00 tips the balance.
    s = JobStore(fleet.partition_root(root, "p00"), create=False)
    s.spool_submit(_spec("j-load"))
    s.close()
    d = fleet.route_submission(root, cfg, now=_T0)
    assert d["kind"] == "load" and d["partition"] == "p01"


def test_route_capacity_filter_heterogeneous_hosts(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    fleet.claim_lease(root, "p00", "small", epoch=1,
                      timeout_s=10.0, now=_T0)
    fleet.claim_lease(root, "p01", "big", epoch=1,
                      timeout_s=10.0, now=_T0)
    for host, cells in (("small", 512), ("big", None)):
        fleet.write_host_record(root, {
            "host": host, "platform": "cpu", "max_cells": cells,
            "t_wall": _T0, "ttl_s": 60.0, "state": "serving"})
    big_cfg = {"nx": 64, "ny": 64, "steps": 60, "backend": "jnp"}
    d = fleet.route_submission(root, big_cfg, now=_T0)
    assert d["kind"] == "capacity"
    assert d["partition"] == "p01" and d["host"] == "big"
    # A grid everyone fits falls through to pure load (the filter
    # only bites when it actually excludes somebody).
    small_cfg = {"nx": 16, "ny": 16, "steps": 60, "backend": "jnp"}
    d = fleet.route_submission(root, small_cfg, now=_T0)
    assert d["kind"] == "load" and d["partition"] == "p00"
    # Stale capacity records stop biting: the small host's claim is
    # old news once past its ttl.
    d = fleet.route_submission(root, big_cfg, now=_T0 + 120.0)
    assert d["kind"] == "load"


# ---------------------------------------------------------------------------
# FleetHost: claims, scheduling, drain/release, work stealing
# ---------------------------------------------------------------------------

def test_fleet_host_claims_serves_and_stamps_host(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    clock = FakeClock()
    launcher = ScriptedLauncher()
    a = _host(root, "hosta", clock, launcher)
    a.step()
    assert sorted(a.leases) == ["p00", "p01"]
    assert a.counters["claims"] == 2 and a.counters["steals"] == 0
    proot = fleet.partition_root(root, "p00")
    store = JobStore(proot, create=False)
    store.spool_submit(_spec("j1"))
    clock.advance(0.1)
    a.step()
    d = launcher.last("j1")
    _finish(store, d, "completed", steps_done=60)
    clock.advance(0.1)
    a.step()
    jobs, anomalies = store.replay()
    assert anomalies == []
    assert jobs["j1"].state == "completed"
    # Every append under the lease carries the host name — the
    # cross-host audit and the per-host metrics fold on it.
    assert all(e.get("host") == "hosta"
               for e in _events(proot, job_id="j1"))
    claims = _events(proot, event="lease_claimed")
    assert claims and claims[0]["epoch"] == 1 \
        and claims[0]["kind"] == "claim"
    _info, fleet_anoms = fleet.audit_fleet(root, now=clock())
    assert fleet_anoms == []
    doc = fleet.fleet_status(root, now=clock())
    by_name = {p["partition"]: p for p in doc["partitions"]}
    assert by_name["p00"]["host"] == "hosta"
    assert by_name["p00"]["counts"].get("completed") == 1
    assert doc["hosts"]["hosta"]["state"] == "serving"
    store.close()
    a.close()


def test_fleet_host_max_partitions_and_graceful_release(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    clock = FakeClock()
    a = _host(root, "hosta", clock, max_partitions=1)
    a.step()
    assert sorted(a.leases) == ["p00"]
    assert a.drain() == 3  # EXIT_PREEMPTED
    # Graceful drain RELEASES: the partition is immediately claimable
    # (no peer waits out a timeout) and the epoch chain continues.
    assert fleet.read_lease(root, "p00") is None
    hosts = fleet.read_host_records(root)
    assert hosts["hosta"]["state"] == "drained"
    b = _host(root, "hostb", clock, max_partitions=1)
    clock.advance(5.0)
    b.step()
    assert sorted(b.leases) == ["p00"]
    claims = _events(fleet.partition_root(root, "p00"),
                     event="lease_claimed")
    assert [c["epoch"] for c in claims] == [1, 2]
    _info, anoms = fleet.audit_fleet(root, now=clock())
    assert anoms == []
    b.close()


def test_work_stealing_claims_abandoned_backlog(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    proot = fleet.partition_root(root, "p00")
    # A previous epoch left committed backlog and no lease (released
    # or reclaimed-and-released): an idle peer steals the work.
    store = JobStore(proot, create=False)
    store.journal.append("lease_claimed", partition="p00", epoch=1,
                         kind="claim", host="gone")
    store.spool_submit(_spec("j-stolen"))
    store.close()
    clock = FakeClock()
    launcher = ScriptedLauncher()
    b = _host(root, "hostb", clock, launcher)
    b.step()
    assert b.counters["steals"] == 1
    claims = _events(proot, event="lease_claimed")
    assert claims[-1]["epoch"] == 2 and claims[-1]["kind"] == "steal"
    assert launcher.last("j-stolen")["attempt"] == 1
    b.close()


# ---------------------------------------------------------------------------
# Cross-host orphan takeover + adoption
# ---------------------------------------------------------------------------

def test_takeover_adopts_and_reruns_inflight_job(tmp_path):
    root = _fleet_root(tmp_path, partitions=1, lease_timeout_s=10.0)
    proot = fleet.partition_root(root, "p00")
    clock = FakeClock()
    launcher_a = ScriptedLauncher()
    a = _host(root, "hosta", clock, launcher_a,
              daemon_opts={"launcher": launcher_a,
                           "heartbeat_timeout_s": 5.0})
    a.step()
    store = JobStore(proot, create=False)
    store.spool_submit(_spec("j-adopt"))
    clock.advance(0.1)
    a.step()
    d1 = launcher_a.last("j-adopt")
    assert d1["attempt"] == 1
    # The worker got one beat out, then hosta wedged: no renewals, no
    # further beats. Past the lease timeout a peer takes over.
    store.write_worker_hb(d1["worker_id"],
                          {"pid": os.getpid(), "t_wall": clock.t})
    clock.advance(11.0)
    launcher_b = ScriptedLauncher()
    b = _host(root, "hostb", clock, launcher_b,
              daemon_opts={"launcher": launcher_b,
                           "heartbeat_timeout_s": 5.0})
    for _ in range(4):
        b.step()
        clock.advance(0.1)
    assert b.counters["takeovers"] == 1
    assert b.counters["hosts_lost"] == 1
    assert b.counters["jobs_adopted"] == 1
    lost = _events(proot, event="host_lost")
    assert len(lost) == 1 and lost[0]["lost_host"] == "hosta" \
        and lost[0]["epoch"] == 2 and lost[0]["host"] == "hostb"
    adopted = _events(proot, event="adopted")
    assert len(adopted) == 1 and adopted[0]["job_id"] == "j-adopt" \
        and adopted[0]["from_host"] == "hosta"
    # The adopted job was orphaned (dead worker, stale-by-absence
    # heartbeat) and re-dispatched by the NEW epoch's claimant.
    d2 = launcher_b.last("j-adopt")
    assert d2["attempt"] == 2
    _finish(store, d2, "completed", steps_done=60)
    clock.advance(0.1)
    b.step()
    jobs, anomalies = store.replay()
    assert anomalies == []
    v = jobs["j-adopt"]
    assert v.state == "completed" and v.attempts == 2
    assert list(v.adoptions) and v.adoptions[0]["from_host"] == "hosta"
    # The wedged host wakes up: its renew fails, it abandons WITHOUT
    # journaling — the partition has exactly one writer again.
    n_events = len(_events(proot))
    a.step()
    assert a.counters["leases_lost"] == 1
    assert a.leases == {} and a.daemons == {}
    assert len(_events(proot)) == n_events
    _info, anoms = fleet.audit_fleet(root, now=clock())
    assert anoms == []
    store.close()
    b.close()


# ---------------------------------------------------------------------------
# Federated audit (heatq --check)
# ---------------------------------------------------------------------------

def test_audit_flags_stale_lease_and_epoch_regression(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    fleet.claim_lease(root, "p00", "dead", epoch=1,
                      timeout_s=1.0, now=_T0 - 60.0)
    info, anoms = fleet.audit_fleet(root, now=_T0)
    assert info["stale_leases"] \
        and info["stale_leases"][0]["host"] == "dead"
    assert any("stale lease" in a for a in anoms)
    # Epoch regression = two live writers (the double-claim the lease
    # protocol exists to prevent).
    proot = fleet.partition_root(root, "p00")
    store = JobStore(proot, create=False)
    store.journal.append("lease_claimed", partition="p00", epoch=2,
                         kind="claim", host="b")
    store.journal.append("lease_claimed", partition="p00", epoch=1,
                         kind="claim", host="c")
    store.close()
    _info, anoms = fleet.audit_fleet(root, now=_T0)
    assert any("epoch regression" in a for a in anoms)
    # ...and the on-disk epoch-1 lease is now BEHIND the journal.
    assert any("behind the journal" in a for a in anoms)


def test_audit_flags_broken_adoption_lineage(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    proot = fleet.partition_root(root, "p00")
    store = JobStore(proot, create=False)
    j = store.journal
    j.append("lease_claimed", partition="p00", epoch=1, kind="claim",
             host="a")
    j.append("accepted", job_id="jx", host="a")
    j.append("dispatched", job_id="jx", worker="w1", attempt=1,
             host="a")
    # An adopted line with NO host_lost of that epoch, appended by a
    # host that never claimed it, over a job that is still running
    # under epoch 1 — three lineage breaks at once.
    j.append("adopted", job_id="jx", epoch=2, from_host="a",
             host="b")
    store.close()
    _info, anoms = fleet.audit_fleet(root, now=_T0)
    assert any("no matching host_lost" in a for a in anoms)


def test_audit_flags_cross_host_double_dispatch(tmp_path):
    root = _fleet_root(tmp_path, partitions=1)
    proot = fleet.partition_root(root, "p00")
    store = JobStore(proot, create=False)
    j = store.journal
    j.append("accepted", job_id="jd", host="a")
    j.append("dispatched", job_id="jd", worker="w1", attempt=1,
             host="a")
    j.append("dispatched", job_id="jd", worker="w9", attempt=1,
             host="b")
    store.close()
    _info, anoms = fleet.audit_fleet(root, now=_T0)
    assert any("double" in a and "dispatch" in a for a in anoms)


def test_heatq_check_exits_2_on_federated_anomaly(tmp_path):
    heatq = os.path.join(_ROOT, "tools", "heatq.py")
    root = _fleet_root(tmp_path, partitions=1)
    p = subprocess.run([sys.executable, heatq, root, "--check",
                        "--json"],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    doc = json.loads(p.stdout)
    assert doc["federated"] and list(doc["partitions"]) == ["p00"]
    fleet.claim_lease(root, "p00", "dead", epoch=1,
                      timeout_s=0.001, now=time.time() - 60.0)
    p = subprocess.run([sys.executable, heatq, root, "--check"],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "STALE LEASE" in p.stdout


# ---------------------------------------------------------------------------
# Fleet observability: metrics_report + slo_gate
# ---------------------------------------------------------------------------

def _served_fleet(tmp_path):
    """One completed job under hosta, journal host-stamped — the
    smallest fleet with a per-host story to report."""
    root = _fleet_root(tmp_path, partitions=2)
    clock = FakeClock()
    launcher = ScriptedLauncher()
    a = _host(root, "hosta", clock, launcher)
    a.step()
    store = JobStore(fleet.partition_root(root, "p00"), create=False)
    store.spool_submit(_spec("j1"))
    clock.advance(0.1)
    a.step()
    _finish(store, launcher.last("j1"), "completed", steps_done=60)
    clock.advance(0.1)
    a.step()
    store.close()
    # Graceful drain: leases released on disk (fake-clock lease
    # stamps would read as ancient to the tools' wall-clock audit).
    a.drain()
    return root


def test_metrics_report_federation_per_host_rows(tmp_path):
    root = _served_fleet(tmp_path)
    mr = os.path.join(_ROOT, "tools", "metrics_report.py")
    p = subprocess.run([sys.executable, mr, root, "--json"],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert doc["federated"] is True
    f = doc["fleet"]
    assert f["jobs_accepted"] == 1 and f["completed"] == 1
    assert f["partitions"] == 2 and f["jobs_adopted"] == 0
    assert f["stale_leases"] == 0
    h = doc["hosts"]["hosta"]
    assert h["lease_claims"] == 2
    assert h["leases_held"] == 0  # drained: releases are on disk
    assert h["completed"] == 1 and h["jobs_adopted"] == 0
    txt = subprocess.run([sys.executable, mr, root],
                         capture_output=True, text=True, timeout=120)
    assert txt.returncode == 0
    assert "hosta" in txt.stdout


def test_slo_gate_federated_tokens_and_heartbeat(tmp_path):
    root = _served_fleet(tmp_path)
    gate = os.path.join(_ROOT, "tools", "slo_gate.py")
    ok = subprocess.run([sys.executable, gate, root,
                         "--fleet", "stale_leases>0,completed<1"],
                        capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # Now strand a lease: the same tokens must trip the gate.
    fleet.claim_lease(root, "p01", "dead", epoch=9,
                      timeout_s=0.001, now=time.time() - 60.0)
    bad = subprocess.run([sys.executable, gate, root,
                          "--fleet", "stale_leases>0"],
                         capture_output=True, text=True, timeout=120)
    assert bad.returncode == 2, bad.stdout + bad.stderr
    assert "stale_leases" in bad.stdout
    # Unknown counters are a loud spec error, never silently held.
    err = subprocess.run([sys.executable, gate, root,
                          "--fleet", "no_such_counter>0"],
                         capture_output=True, text=True, timeout=120)
    assert err.returncode == 1


# ---------------------------------------------------------------------------
# Peer-cache routing end-to-end (inline workers, real solver)
# ---------------------------------------------------------------------------

def test_fleet_exact_cache_route_zero_dispatch(tmp_path):
    root = _fleet_root(tmp_path, partitions=2)
    proot = fleet.partition_root(root, "p00")
    spawns = []
    a = _host(root, "hosta", time.time,
              launcher=inline_launcher(proot, spawns=spawns),
              max_partitions=1, slots=1,
              daemon_opts={"launcher": inline_launcher(proot,
                                                       spawns=spawns),
                           "worker_env": {"JAX_PLATFORMS": "cpu"}})
    a.step()
    assert sorted(a.leases) == ["p00"]
    cfg = {"nx": 12, "ny": 12, "steps": 30, "backend": "jnp"}

    def run(job_id):
        route = fleet.route_submission(root, cfg)
        store = JobStore(route["root"], create=False)
        store.spool_submit(JobSpec(job_id=job_id, config=dict(cfg),
                                   route=route))
        store.close()
        deadline = time.time() + 60.0
        while time.time() < deadline:
            a.step()
            jobs, _ = JobStore(proot, create=False).replay()
            v = jobs.get(job_id)
            if v is not None and v.terminal:
                return route, v
            time.sleep(0.01)
        raise TimeoutError(job_id)

    route1, v1 = run("donor")
    assert route1["partition"] == "p00" and v1.state == "completed"
    assert spawns == ["donor"]
    # The identical spec routes to the partition whose cache serves it
    # outright — and admission completes it with ZERO dispatches.
    route2, v2 = run("hit")
    assert route2["kind"] == "exact" and route2["partition"] == "p00"
    assert route2["donor_key"] is not None
    assert v2.state == "completed"
    assert spawns == ["donor"]  # no second worker fleet-wide
    hits = _events(proot, job_id="hit", event="cache_hit")
    assert hits and hits[0].get("donor") == "donor"
    _info, anoms = fleet.audit_fleet(root)
    assert anoms == []
    a.close()


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_fleet_init_and_status(tmp_path, capsys):
    from parallel_heat_tpu.service import cli as svc_cli

    root = str(tmp_path / "f")
    rc = svc_cli.main(["fleet-init", "--fleet", root,
                       "--partitions", "3", "--lease-timeout", "7"])
    assert rc == 0
    assert "3 partition(s)" in capsys.readouterr().out
    assert fleet.is_fleet_root(root)
    assert fleet.fleet_doc(root)["lease_timeout_s"] == 7.0
    rc = svc_cli.main(["fleet-status", "--fleet", root, "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["partitions"]) == 3
    rc = svc_cli.main(["fleet-status", "--fleet", root])
    assert rc == 0
    assert "p02" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Real processes (slow tier — the fast suite above stays fake-clocked)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_serve_subprocess_round_trip(tmp_path):
    root = _fleet_root(tmp_path, partitions=2, lease_timeout_s=5.0)
    env = dict(os.environ, PYTHONPATH=_ROOT, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "parallel_heat_tpu.cli", "fleet-serve",
         "--fleet", root, "--host", "h1", "--slots", "1",
         "--poll-interval", "0.05", "--lease-renew", "0.25",
         "--worker-heartbeat", "0.25", "--heartbeat-timeout", "2.0"],
        env=env, cwd=_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        verdict = client.fleet_submit(
            root, {"nx": 12, "ny": 12, "steps": 30, "backend": "jnp"},
            job_id="rt", accept_timeout_s=60.0)
        assert verdict["accepted"], verdict
        v = client.fleet_wait(root, "rt", timeout_s=90.0)
        assert v.state == "completed"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 3  # EXIT_PREEMPTED: graceful drain, leases released
    assert fleet.read_lease(root, "p00") is None
    heatq = os.path.join(_ROOT, "tools", "heatq.py")
    p = subprocess.run([sys.executable, heatq, root, "--check"],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
