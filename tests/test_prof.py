"""prof plane: static work models, the attribution join, and the
roofline-relative efficiency alert (ISSUE 19's acceptance surface).

What is pinned here:

- the work model's arithmetic: flops/cell (3*ndim+1), HBM bytes/step
  (profiling's bytes_per_cell accounting), sharded ICI bytes per
  exchange scaling with halo depth, and the TuneDB content-address
  identity (one key joins tuned entries, measured rows and models);
- the attribution join: lane shares, the dominant-bound argmax, the
  gap_s clamp (sync-loop gaps may exceed the chunk wall), and the
  null convention for sub-resolution chunks;
- the degradation ladder: embedded model -> rebuilt from config ->
  named reason; foreign streams degrade the report, never throw;
- observation-only: profile emission between two identical solves
  changes neither the result bits nor the ``_build_runner`` miss
  count (the telemetry contract extended to the prof plane);
- the series harvester folds profile events into the roofline_frac
  gauge + per-bound counters, and ``efficiency_regression`` trips
  exactly once on a doctored sub-roofline window while staying
  silent on a clean one — with NO tuning DB (relative-to-own-history
  by design: CPU runs price the v5e roofline, so absolute floors
  would always trip);
- ``tools/heatprof.py`` on the committed artifact names a dominant
  bound per segment and the shared --fail-on grammar gates it;
- the heatlint default scan paths cover the prof package.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.prof import (
    BOUNDS,
    attribute_chunk,
    attribute_stream,
    work_model,
)
from parallel_heat_tpu.prof.model import valid_model

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_T0 = 1_700_000_000.0
_BASE = dict(nx=16, ny=16, backend="jnp")


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# Work model arithmetic
# ---------------------------------------------------------------------------

def test_work_model_2d_f32_pins():
    m = work_model(HeatConfig(nx=64, ny=64, steps=10, backend="jnp"))
    assert m["site"] == "single_2d"
    assert m["ndim"] == 2 and m["flops_per_cell"] == 7  # 5-point star
    assert m["cells"] == 64 * 64
    assert m["bytes_per_cell"] == 8  # read + write, f32
    assert m["hbm_bytes_per_step"] == 64 * 64 * 8
    assert m["flops_per_step"] == 7 * 64 * 64
    assert m["n_shards"] == 1
    assert m["ici_bytes_per_exchange"] == 0
    assert m["exchanges_per_step"] == 0.0
    # Every generation in the tpu_params table is bandwidth-bound on
    # the plain stencil, so the roofline rate is exactly the HBM peak
    # over bytes/cell — the same identity tools/vpu_roofline.py pins.
    assert m["predicted_bound"] == "hbm"
    assert m["roofline_mcells_steps_per_s"] == pytest.approx(
        m["peaks"]["hbm_stream_bytes_per_s"] / 8 / 1e6)
    # Identity: the model is addressed by the same content hash TuneDB
    # uses for this (site, topology, geometry) — joinable by content.
    from parallel_heat_tpu import tune

    key, _doc = tune.tune_key(m["site"], m["topology"], m["geometry"])
    assert m["tune_key"] == key and len(key) == 40


def test_work_model_3d_and_bf16():
    m3 = work_model(HeatConfig(nx=32, ny=32, nz=32, steps=5,
                               backend="jnp"))
    assert m3["ndim"] == 3 and m3["flops_per_cell"] == 10  # 7-point
    assert m3["cells"] == 32 ** 3
    mb = work_model(HeatConfig(nx=64, ny=64, steps=5,
                               dtype="bfloat16", backend="jnp"))
    assert mb["bytes_per_cell"] == 4  # half the f32 traffic, and with
    # it the stencil flips from bandwidth- to compute-bound on the v5e
    # ratios (4/650e9 < 1/140e9 per cell): the roofline is the VPU peak.
    assert mb["predicted_bound"] == "compute"
    assert mb["roofline_mcells_steps_per_s"] == pytest.approx(
        mb["peaks"]["vpu_cells_per_s"] / 1e6)


def test_work_model_sharded_ici_scales_with_halo_depth():
    d1 = work_model(HeatConfig(nx=64, ny=64, steps=10,
                               mesh_shape=(2, 2), halo_depth=1,
                               backend="jnp"))
    assert d1["site"] == "halo_overlap" and d1["n_shards"] == 4
    # Per device, per partitioned axis: 2 directions x depth rows of
    # the 32-wide local block x 4 bytes; both axes partitioned.
    assert d1["ici_bytes_per_exchange"] == 2 * (2 * 1 * 32 * 4)
    assert d1["exchanges_per_step"] == 1.0
    d2 = work_model(HeatConfig(nx=64, ny=64, steps=10,
                               mesh_shape=(2, 2), halo_depth=2,
                               backend="jnp"))
    # K-deep halos: 2x the bytes per exchange, half the exchanges.
    assert d2["ici_bytes_per_exchange"] == 2 * d1["ici_bytes_per_exchange"]
    assert d2["exchanges_per_step"] == 0.5
    assert d2["halo_depth"] == 2
    assert d1["tune_key"] != d2["tune_key"]  # depth is in the geometry


def test_work_model_mg_lanes_partitioned_arithmetic():
    """Implicit sharded models carry the per-level V-cycle lane
    decomposition: sweeps per level (2*nu, coarsest nu+_COARSE_SWEEPS),
    12 B/cell f32 HBM per sweep, and for partitioned levels one 1-deep
    exchange per sweep plus two seam/residual extras — priced against
    the plan's padded block extents, with replicated levels at the
    honest divisor-1 zero-speedup accounting."""
    from parallel_heat_tpu.config import multigrid_level_shapes
    from parallel_heat_tpu.ops import multigrid_sharded
    from parallel_heat_tpu.ops.multigrid import _COARSE_SWEEPS

    cfg = HeatConfig(nx=64, ny=64, steps=5, backend="jnp",
                     mesh_shape=(2, 4), scheme="backward_euler",
                     mg_partition="partitioned")
    m = work_model(cfg)
    assert m["site"] == "mg_partition" and m["n_shards"] == 8
    mg = m["mg"]
    assert mg["work_unit"] == "vcycle"
    assert mg["mg_partition"] == "partitioned"

    shapes = multigrid_level_shapes(cfg.validate().shape, cfg.mg_levels)
    n = len(shapes)
    assert mg["n_levels"] == n
    assert mg["level_cells"] == [(s[0] - 2) * (s[1] - 2) for s in shapes]
    nu = cfg.mg_smooth
    assert mg["sweeps_per_cycle"] == (
        [2 * nu] * (n - 1) + [nu + _COARSE_SWEEPS])
    # Every level is carried in f32: u-read + b-read + u-write per
    # sweep = 12 B/cell regardless of the storage dtype.
    assert mg["hbm_bytes_per_cycle"] == sum(
        c * s * 12 for c, s in zip(mg["level_cells"],
                                   mg["sweeps_per_cycle"]))

    # 64^2 is below the analytic profitability threshold, so the plan
    # partitions exactly the forced floor of one level; its ICI bytes
    # come from that level's block perimeter alone.
    plan = multigrid_sharded.partition_plan(cfg.validate(),
                                            min_partitioned=1)
    assert mg["partitioned_levels"] == plan["partitioned_levels"] == 1
    blk = plan["levels"][0]["block_shape"]
    # (64/2, 64/4) top-level blocks plus the 1-deep exchange ring.
    assert list(blk) == [34, 18]
    perim = 2 * blk[1] * 4 + 2 * blk[0] * 4  # both axes partitioned
    n_ex = mg["sweeps_per_cycle"][0] + 2  # +residual +seam exchanges
    assert mg["exchanges_per_cycle"] == n_ex
    assert mg["ici_bytes_per_cycle"] == n_ex * perim
    # Lane times: the partitioned level divides by the shard count,
    # replicated levels run full-shape on every device (divisor 1).
    pk = m["peaks"]
    t_hbm = sum(
        c * s * 12 / (pk["hbm_stream_bytes_per_s"]
                      * (8 if l < 1 else 1))
        for l, (c, s) in enumerate(zip(mg["level_cells"],
                                       mg["sweeps_per_cycle"])))
    assert m["t_hbm_s"] == pytest.approx(t_hbm)
    assert m["t_ici_s"] == pytest.approx(
        n_ex * perim / pk["ici_bytes_per_s"]
        + n_ex * 2.0 * pk["collective_latency_s"])

    # Replicated sharded implicit: same site (the decision context is
    # the mg_partition tune site), zero partitioned levels, zero ICI.
    r = work_model(cfg.replace(mg_partition="replicated"))
    assert r["site"] == "mg_partition"
    assert r["mg"]["partitioned_levels"] == 0
    assert r["mg"]["ici_bytes_per_cycle"] == 0
    assert r["mg"]["exchanges_per_cycle"] == 0
    assert r["t_ici_s"] == 0.0

    # Solo implicit keys the single-device site and models no ICI;
    # explicit configs carry no mg block at all.
    solo = work_model(HeatConfig(nx=64, ny=64, steps=5, backend="jnp",
                                 scheme="backward_euler"))
    assert solo["site"] == "single_2d"
    assert solo["mg"]["mg_partition"] is None
    assert solo["mg"]["partitioned_levels"] == 0
    expl = work_model(HeatConfig(nx=64, ny=64, steps=5, backend="jnp"))
    assert expl["mg"] is None


def test_valid_model_gate():
    m = work_model(HeatConfig(steps=5, **_BASE))
    assert valid_model(m) is m
    assert valid_model(None) is None
    assert valid_model("not a dict") is None
    assert valid_model(dict(m, model_version=99)) is None
    assert valid_model(dict(m, roofline_mcells_steps_per_s=0)) is None


# ---------------------------------------------------------------------------
# Attribution join
# ---------------------------------------------------------------------------

def _model(**kw):
    base = {"model_version": 1, "tune_key": "k" * 40,
            "site": "single_2d", "cells": 1_000_000,
            "roofline_mcells_steps_per_s": 100.0,
            "t_compute_s": 1e-9, "t_hbm_s": 2e-9, "t_ici_s": 0.0}
    base.update(kw)
    return base


def test_attribute_chunk_lane_shares_and_bound():
    m = _model()
    # 1e6 cells x 10 steps / 0.5 s = 20 Mcells*steps/s -> 0.2 of roof.
    seg = attribute_chunk({"step": 20, "steps": 10, "wall_s": 0.5,
                           "gap_s": 0.1}, m)
    assert seg["prof_schema"] == 1
    assert seg["mcells_steps_per_s"] == pytest.approx(20.0)
    assert seg["roofline_frac"] == pytest.approx(0.2)
    assert seg["shares"]["host"] == pytest.approx(0.2)
    assert seg["shares"]["hbm"] == pytest.approx(0.8)  # t_hbm slower
    assert seg["shares"]["compute"] == 0.0
    assert seg["bound"] == "hbm" and seg["bound"] in BOUNDS
    # A producer-measured exchange_s wins the ici lane.
    seg = attribute_chunk({"steps": 10, "wall_s": 0.5, "gap_s": 0.05,
                           "exchange_s": 0.3}, m)
    assert seg["shares"]["ici"] == pytest.approx(0.6)
    assert seg["bound"] == "ici"
    # Sync-loop gap_s measures BETWEEN-chunk host time and may exceed
    # this chunk's wall: the host lane clamps at 100%.
    seg = attribute_chunk({"steps": 10, "wall_s": 0.5, "gap_s": 2.0}, m)
    assert seg["shares"]["host"] == 1.0 and seg["bound"] == "host"
    # A compute-heavier model routes the device lane to compute.
    seg = attribute_chunk({"steps": 10, "wall_s": 0.5},
                          _model(t_compute_s=3e-9))
    assert seg["bound"] == "compute"
    # Null convention: a sub-resolution chunk is unmeasured, not wrong.
    seg = attribute_chunk({"steps": 0, "wall_s": 0.0}, m)
    assert seg["mcells_steps_per_s"] is None
    assert seg["roofline_frac"] is None and seg["bound"] is None


def test_attribute_stream_degradation_ladder():
    cfg = HeatConfig(steps=20, **_BASE)
    m = work_model(cfg)
    chunk = {"event": "chunk", "step": 10, "steps": 10, "wall_s": 0.2}
    # Rung 1: the header's embedded model is authoritative.
    doc = attribute_stream([
        {"event": "run_header", "explain": {"work_model": m}},
        chunk, dict(chunk, step=20)])
    assert doc["degraded"] is None and not doc["live_profile"]
    assert len(doc["segments"]) == 2
    assert doc["segments"][0]["tune_key"] == m["tune_key"]
    assert doc["roofline_frac"]["n"] == 2
    assert doc["model_vs_measured"]["achieved_fraction"] > 0
    # Rung 2: no embedded model -> rebuilt from the header config,
    # and the report says so.
    doc = attribute_stream([
        {"event": "run_header", "config": json.loads(cfg.to_json())},
        chunk])
    assert "rebuilt" in doc["degraded"]
    assert doc["segments"][0]["tune_key"] == m["tune_key"]
    # Rung 3: nothing to rebuild from -> named reason, empty join.
    doc = attribute_stream([{"event": "run_header"}, chunk])
    assert "no work model" in doc["degraded"]
    assert doc["segments"] == [] and doc["roofline_frac"] is None
    # No header at all; foreign lines never throw.
    doc = attribute_stream([chunk, "garbage", 17, {"event": "huh"}])
    assert doc["degraded"] == "no run_header in stream"
    # Live profile events are the producer's own join: used verbatim,
    # chunks are NOT re-attributed on top.
    prof = {"event": "profile", "prof_schema": 1, "step": 10,
            "steps": 10, "wall_s": 0.2, "roofline_frac": 0.4,
            "bound": "hbm", "mcells_steps_per_s": 40.0}
    doc = attribute_stream([
        {"event": "run_header", "explain": {"work_model": m}},
        chunk, prof])
    assert doc["live_profile"] and len(doc["segments"]) == 1
    assert doc["bound_histogram"] == {"hbm": 1}
    assert doc["worst"]["roofline_frac"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# Emission: profile events ride the stream, observation-only
# ---------------------------------------------------------------------------

def test_profile_emission_is_observation_only(tmp_path):
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.solver import solve_stream
    from parallel_heat_tpu.utils.telemetry import Telemetry

    cfg = HeatConfig(steps=30, **_BASE)
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=10)]
    misses_before = solver._build_runner.cache_info().misses
    with Telemetry(tmp_path / "t.jsonl") as tel:
        instr = [r.to_numpy()
                 for r in solve_stream(cfg, chunk_steps=10,
                                       telemetry=tel)]
    assert solver._build_runner.cache_info().misses == misses_before
    for a, b in zip(plain, instr):
        np.testing.assert_array_equal(a, b)
    ev = _events(tmp_path / "t.jsonl")
    profs = [e for e in ev if e["event"] == "profile"]
    assert [p["step"] for p in profs] == [10, 20, 30]
    for p in profs:
        assert p["prof_schema"] == 1
        assert p["steps"] == 10 and p["wall_s"] > 0
        assert p["bound"] in BOUNDS
        assert 0 < p["roofline_frac"] < 1
        assert p["shares"][p["bound"]] == max(p["shares"].values())
    # One identity across the stream: the header's embedded model is
    # the model the live segments were priced against.
    header = next(e for e in ev if e["event"] == "run_header")
    wm = header["explain"]["work_model"]
    assert wm["tune_key"] == profs[0]["tune_key"]
    assert valid_model(wm) is not None


# ---------------------------------------------------------------------------
# Fleet plane: series harvest + efficiency_regression
# ---------------------------------------------------------------------------

def _prof_line(t, frac, bound="hbm"):
    return {"schema": 2, "event": "profile", "t_wall": t,
            "prof_schema": 1, "roofline_frac": frac, "bound": bound}


def test_harvest_folds_profile_events(tmp_path):
    from parallel_heat_tpu.obs.series import harvest
    from parallel_heat_tpu.service.store import JobStore

    root = str(tmp_path / "q")
    JobStore(root, create=True)
    tdir = os.path.join(root, "telemetry")
    os.makedirs(tdir, exist_ok=True)
    with open(os.path.join(tdir, "j1.jsonl"), "w") as f:
        for i, frac in enumerate([0.5, 0.6, float("nan")]):
            f.write(json.dumps(_prof_line(_T0 + i, frac)) + "\n")
        f.write(json.dumps(_prof_line(_T0 + 3, 0.7, bound="ici"))
                + "\n")
        f.write(json.dumps(_prof_line(_T0 + 4, 0.7, bound="weird"))
                + "\n")
    samples, _cur = harvest(root, {}, now=_T0 + 10)
    fracs = [s for s in samples if s["counter"] == "roofline_frac"]
    # NaN dropped; the foreign-bound line still carries a valid gauge.
    assert [s["value"] for s in fracs] == [0.5, 0.6, 0.7, 0.7]
    assert all(s["kind"] == "gauge" for s in fracs)
    bounds = sorted(s["counter"] for s in samples
                    if s["counter"].startswith("bound_"))
    # The NaN-frac line still counts its (valid) bound; the foreign
    # bound name is dropped.
    assert bounds == ["bound_hbm"] * 3 + ["bound_ici"]


def _s(t, counter, value, kind="gauge"):
    return {"t": t, "host": "", "part": "", "counter": counter,
            "kind": kind, "value": value}


def _h(t, samples):
    return {"schema": 1, "event": "harvest", "t": t,
            "samples": samples, "cursors": {"parts": {}}}


def _job_with_fracs(root, jid, t0, before, during):
    """One dispatched+completed job on a root whose roofline_frac
    series reads ``before`` ahead of the dispatch and ``during``
    inside the job's window."""
    from parallel_heat_tpu.service.store import JobStore

    store = JobStore(root, create=not os.path.isdir(root))
    j = store.journal
    j.append("accepted", job_id=jid, t_wall=t0, hbm_bytes=1)
    j.append("dispatched", job_id=jid, t_wall=t0 + 1,
             worker=f"w-{jid}", attempt=1)
    j.append("completed", job_id=jid, t_wall=t0 + 50)
    j.close()
    samples = [_s(t0 - 20 + i, "roofline_frac", v)
               for i, v in enumerate(before)]
    samples += [_s(t0 + 2 + i * 4, "roofline_frac", v)
                for i, v in enumerate(during)]
    return _h(t0 + 60, samples)


def test_efficiency_regression_tp_tn_and_latch(tmp_path):
    from parallel_heat_tpu.obs.alerts import AlertEngine
    from parallel_heat_tpu.obs.series import obs_dir_for, reduce_obs
    from parallel_heat_tpu.service.store import read_journal_file

    # TP: window mean 0.001 vs own baseline 0.005 -> collapse. The
    # absolute values are CPU-scale tiny ON PURPOSE: the alert is
    # relative to the partition's own history (the v5e-priced roofline
    # makes every CPU fraction ~1e-3), so no TuneDB and no floor.
    root = str(tmp_path / "q")
    ev = _job_with_fracs(root, "slow", _T0,
                         before=[0.005, 0.005, 0.005],
                         during=[0.001, 0.001, 0.001])
    state = reduce_obs([ev])
    with AlertEngine(obs_dir_for(root)) as eng:
        tripped = eng.evaluate(state, root=root, now=_T0 + 100)
        assert [a["key"] for a in tripped] == \
            ["efficiency_regression||slow"]
        d = tripped[0]["detail"]
        assert d["observed_roofline_frac"] == pytest.approx(0.001)
        assert d["baseline_roofline_frac"] == pytest.approx(0.005)
        # The latch: exactly one journaled trip, ever (re-evaluating
        # the same still-true condition is not news).
        for _ in range(3):
            assert eng.evaluate(state, root=root,
                                now=_T0 + 200) == []
        assert set(eng.active()) == {"efficiency_regression||slow"}
    events, _bad, _torn = read_journal_file(
        os.path.join(obs_dir_for(root), "alerts.jsonl"))
    assert sum(1 for e in events
               if e.get("event") == "alert_tripped") == 1

    # TN: a clean stream (window at ~baseline) stays silent.
    root2 = str(tmp_path / "q2")
    ev2 = _job_with_fracs(root2, "fine", _T0,
                          before=[0.005, 0.005, 0.005],
                          during=[0.0045, 0.005, 0.0055])
    with AlertEngine(obs_dir_for(root2)) as eng:
        assert eng.evaluate(reduce_obs([ev2]), root=root2,
                            now=_T0 + 100) == []

    # No baseline (first-ever job on the partition): no verdict.
    root3 = str(tmp_path / "q3")
    ev3 = _job_with_fracs(root3, "first", _T0, before=[],
                          during=[0.001, 0.001, 0.001])
    with AlertEngine(obs_dir_for(root3)) as eng:
        assert eng.evaluate(reduce_obs([ev3]), root=root3,
                            now=_T0 + 100) == []


# ---------------------------------------------------------------------------
# heatprof CLI on the committed artifact
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_heatprof_cli_on_committed_artifact():
    art = os.path.join(_ROOT, "runs", "prof_r19_cpu.jsonl")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "heatprof.py"),
         art, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)["runs"][0]
    assert doc["model"]["site"] == "single_2d"
    assert doc["segments"] and doc["bound_histogram"]
    for seg in doc["segments"]:
        assert seg["bound"] in BOUNDS
    assert 0 < doc["roofline_frac"]["mean"] < 1
    # The shared --fail-on grammar gates the same report: a roofline
    # floor a CPU run cannot meet exits 2 (the doctored-gate smoke).
    gated = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "heatprof.py"),
         art, "--fail-on", "roofline_frac<0.5"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert gated.returncode == 2, gated.stderr[-2000:]
    assert "roofline_frac" in gated.stderr


# ---------------------------------------------------------------------------
# Hygiene scan scope
# ---------------------------------------------------------------------------

def test_hl2xx_scan_scope_covers_prof_package():
    # Same pin as the obs package: the AST hygiene rules must audit
    # the prof plane like everything else (its emission path runs
    # inside solve_stream's loop — a stray blocking call there would
    # tax every instrumented run).
    from parallel_heat_tpu.analysis.astlint import (
        _iter_py_files, default_scan_paths)

    files = {os.path.relpath(p).replace(os.sep, "/") for p in
             _iter_py_files(default_scan_paths())}
    assert {"parallel_heat_tpu/prof/__init__.py",
            "parallel_heat_tpu/prof/model.py",
            "parallel_heat_tpu/prof/attrib.py"} <= files
