"""K-deep halo exchange (parallel/temporal.py) vs single-device runs.

The temporal path evaluates the same jnp textbook tree per step, so its
results must be bitwise identical to both the 1-deep sharded path and a
single-device run — including across chunk remainders (n % K != 0),
converge mode, and domain-edge blocks (where ppermute supplies zeros
the Dirichlet mask must neutralize).
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.solver import solve_stream

MESHES = [(2, 1), (1, 2), (2, 2), (2, 4), (4, 2)]


def _want(nx, ny, **kw):
    return solve(HeatConfig(nx=nx, ny=ny, backend="jnp", **kw)).to_numpy()


@pytest.mark.parametrize("mesh", MESHES)
@pytest.mark.parametrize("depth", [2, 4])
def test_deep_halo_fixed_equals_single(mesh, depth):
    # steps chosen to exercise both full rounds and a remainder round
    for steps in (depth * 3, depth * 3 + 1):
        want = _want(32, 32, steps=steps)
        got = solve(
            HeatConfig(nx=32, ny=32, steps=steps, backend="jnp",
                       mesh_shape=mesh, halo_depth=depth)
        ).to_numpy()
        np.testing.assert_array_equal(got, want)


def test_deep_halo_converge_equals_single():
    kw = dict(steps=10_000, converge=True, check_interval=20)
    want = solve(HeatConfig(nx=20, ny=20, backend="jnp", **kw))
    got = solve(HeatConfig(nx=20, ny=20, backend="jnp", mesh_shape=(2, 2),
                           halo_depth=4, **kw))
    assert got.converged == want.converged
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_deep_halo_check_interval_not_multiple_of_depth():
    # ci=20, K=8 -> rounds of 8+8+4 per check; schedule must be exact
    kw = dict(steps=200, converge=True, check_interval=20, eps=1e-9)
    want = solve(HeatConfig(nx=24, ny=24, backend="jnp", **kw))
    got = solve(HeatConfig(nx=24, ny=24, backend="jnp", mesh_shape=(2, 2),
                           halo_depth=8, **kw))
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_deep_halo_depth_equals_block_extent():
    # halo as deep as the whole block: every exchanged strip is a full
    # block (the hardest corner case the validator admits)
    want = _want(16, 16, steps=13)
    got = solve(
        HeatConfig(nx=16, ny=16, steps=13, backend="jnp",
                   mesh_shape=(2, 2), halo_depth=8)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_deep_halo_rejects_depth_beyond_block():
    with pytest.raises(ValueError, match="halo_depth"):
        HeatConfig(nx=16, ny=16, mesh_shape=(4, 4), halo_depth=5).validate()
    with pytest.raises(ValueError, match="halo_depth"):
        HeatConfig(nx=16, ny=16, halo_depth=0).validate()
    with pytest.raises(ValueError, match="halo_depth"):
        # 3D: depth bounded by the smallest block extent too
        HeatConfig(nx=16, ny=16, nz=16, mesh_shape=(2, 2, 4),
                   halo_depth=5).validate()


def test_deep_halo_with_solve_stream():
    cfg = HeatConfig(nx=32, ny=32, steps=50, backend="jnp",
                     mesh_shape=(2, 2), halo_depth=4)
    want = _want(32, 32, steps=50)
    last = None
    for last in solve_stream(cfg, chunk_steps=20):
        pass
    assert last.steps_run == 50
    np.testing.assert_array_equal(last.to_numpy(), want)


def test_deep_halo_bf16_storage():
    # per-step storage rounding must match the single-device bf16 run
    kw = dict(steps=17, dtype="bfloat16")
    want = _want(32, 32, **kw)
    got = solve(
        HeatConfig(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4),
                   halo_depth=4, **kw)
    ).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_deep_halo_pallas_round_equals_jnp():
    # kernel G (Mosaic round, interpret mode on CPU) vs the jnp rounds:
    # same semantics to a few ulp; vs single-device for ground truth.
    kw = dict(nx=32, ny=32, steps=24, dtype="float32")
    want = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    got = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2),
                           halo_depth=8, **kw)).to_numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_deep_halo_pallas_remainder_and_converge():
    # remainder rounds (depth < SUB) fall back to jnp inside the same
    # run; converge mode exercises the kernel's fused core residual
    kw = dict(nx=32, ny=32, steps=2000, converge=True, check_interval=20)
    want = solve(HeatConfig(backend="jnp", **kw))
    got = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2),
                           halo_depth=8, **kw))
    assert got.converged == want.converged
    assert got.steps_run == want.steps_run
    # ~2000 steps of one-ulp-per-step backend drift (factored vs
    # textbook combine): same loose contract as the long-run pallas
    # tests in test_pallas.py
    np.testing.assert_allclose(got.to_numpy(), want.to_numpy(),
                               rtol=1e-4, atol=0.1)


def test_deep_halo_pallas_builder_engages():
    # the kernel-G builder must actually accept the canonical geometry
    from parallel_heat_tpu.ops.pallas_stencil import _build_temporal_block

    assert _build_temporal_block((16, 16), "float32", 0.1, 0.1,
                                 (32, 32), 8) is not None
    # and decline non-sublane depths (jnp rounds take over)
    assert _build_temporal_block((16, 16), "float32", 0.1, 0.1,
                                 (32, 32), 4) is None


@pytest.mark.parametrize("mesh", [(2, 2, 2), (2, 1, 2), (1, 2, 4)])
def test_deep_halo_3d_equals_single(mesh):
    for steps in (6, 7):
        want = solve(HeatConfig(nx=12, ny=12, nz=16, steps=steps,
                                backend="jnp")).to_numpy()
        got = solve(
            HeatConfig(nx=12, ny=12, nz=16, steps=steps, backend="jnp",
                       mesh_shape=mesh, halo_depth=3)
        ).to_numpy()
        np.testing.assert_array_equal(got, want)


def test_deep_halo_3d_converge_equals_single():
    kw = dict(steps=2000, converge=True, check_interval=20)
    want = solve(HeatConfig(nx=10, ny=10, nz=10, backend="jnp", **kw))
    got = solve(HeatConfig(nx=10, ny=10, nz=10, backend="jnp",
                           mesh_shape=(2, 1, 1), halo_depth=5, **kw))
    assert got.converged == want.converged
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_deep_halo_reduces_collectives():
    """One K-deep round advances K steps with the SAME 4 ppermutes a
    single 1-deep step needs — the K x communication reduction, counted
    directly in the traced programs (loop-free jaxprs)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from parallel_heat_tpu.parallel.halo import block_step_2d
    from parallel_heat_tpu.parallel.temporal import block_multistep_2d
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh
    from parallel_heat_tpu.utils.compat import shard_map as _shard_map

    mesh = make_heat_mesh((2, 2))
    spec = P("x", "y")
    K = 4
    kw = dict(mesh_shape=(2, 2), grid_shape=(32, 32), cx=0.1, cy=0.1,
              axis_names=("x", "y"))

    def deep(u):
        bidx = (jax.lax.axis_index("x"), jax.lax.axis_index("y"))
        return block_multistep_2d(u, K, block_index=bidx, **kw)

    def shallow(u):
        bidx = (jax.lax.axis_index("x"), jax.lax.axis_index("y"))
        for _ in range(K):  # K steps, unrolled: K x 4 ppermutes
            u = block_step_2d(u, block_index=bidx, **kw)
        return u

    import jax.numpy as jnp

    u = jnp.zeros((16, 16), jnp.float32)
    n_deep = str(jax.make_jaxpr(
        _shard_map(deep, mesh=mesh, in_specs=spec, out_specs=spec))(u)
    ).count("ppermute")
    n_shallow = str(jax.make_jaxpr(
        _shard_map(shallow, mesh=mesh, in_specs=spec, out_specs=spec))(u)
    ).count("ppermute")
    assert n_deep == 4, n_deep
    assert n_shallow == 4 * K, n_shallow


def test_deep_halo_explicit_pallas_requires_sublane_depth():
    with pytest.raises(ValueError, match="sublane|Mosaic"):
        HeatConfig(nx=32, ny=32, mesh_shape=(2, 2), halo_depth=4,
                   backend="pallas").validate()
    # depth == sublane count validates
    HeatConfig(nx=32, ny=32, mesh_shape=(2, 2), halo_depth=8,
               backend="pallas").validate()
    HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=16,
               dtype="bfloat16", backend="pallas").validate()


def test_resolve_halo_depth_matrix():
    """Pin the auto (halo_depth=None) resolution matrix.

    Auto deepens to the dtype's sublane count exactly when the Mosaic
    block kernel would run: resolved backend pallas + mesh + admitting
    geometry. Everything else resolves to 1.
    """
    from parallel_heat_tpu.solver import _resolve_halo_depth

    r = _resolve_halo_depth
    # pallas + mesh + admitting geometry -> sublane depth
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2)), "pallas") == 8
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                        dtype="bfloat16"), "pallas") == 16
    # jnp backend keeps the per-step overlap split
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2)), "jnp") == 1
    # single device: no exchange to deepen
    assert r(HeatConfig(nx=64, ny=64), "pallas") == 1
    # block smaller than the sublane depth -> clamp to 1
    assert r(HeatConfig(nx=8, ny=8, mesh_shape=(2, 2)), "pallas") == 1
    # explicit value always wins
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=3),
             "pallas") == 3
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=1),
             "jnp") == 1
    # 3D: kernel H's scored sweep picks a deep exchange
    assert r(HeatConfig(nx=32, ny=32, nz=128, mesh_shape=(2, 2, 1)),
             "pallas") > 1
    assert r(HeatConfig(nx=32, ny=32, nz=128, mesh_shape=(2, 2, 1)),
             "jnp") == 1


def test_auto_depth_solve_matches_explicit_depth():
    # A bare sharded pallas config (auto depth) must match the same
    # solve with the depth pinned explicitly and the jnp oracle.
    kw = dict(nx=32, ny=32, steps=17)
    import numpy as np

    oracle = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    auto = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2),
                            **kw)).to_numpy()
    pinned = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2),
                              halo_depth=8, **kw)).to_numpy()
    np.testing.assert_array_equal(auto, pinned)
    np.testing.assert_allclose(auto, oracle, rtol=1e-4, atol=1e-3)


def test_explain_reports_auto_depth():
    from parallel_heat_tpu.solver import explain

    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="pallas"))
    assert out["halo_depth"] == "8 (auto)"
    assert "kernel G" in out["path"]
    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="jnp"))
    assert out["halo_depth"] == "1 (auto)"


def test_kernel_g_fused_matches_circular_legacy_and_jnp():
    # The fused-assembly kernel G must agree with the assembled
    # circular layout AND the legacy padded layout bit-for-bit (same
    # arithmetic, different data transport) and with the jnp oracle to
    # stencil-reassociation tolerance.
    from parallel_heat_tpu.ops import pallas_stencil as ps
    from parallel_heat_tpu.parallel.mesh import AXIS_NAMES

    kw = dict(nx=32, ny=32, steps=17)
    cfg = HeatConfig(backend="pallas", mesh_shape=(2, 2), halo_depth=8,
                     **kw)
    kind, _, _ = ps.pick_block_temporal_2d(cfg, AXIS_NAMES[:2])
    assert kind == "G-uni"  # round 4: uniform-window layout preferred
    assert ps.pick_block_temporal_2d_deferred(cfg, AXIS_NAMES[:2]) \
        is not None  # 16-row blocks host the overlapped round
    overlapped = solve(cfg).to_numpy()
    oracle = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    np.testing.assert_allclose(overlapped, oracle, rtol=1e-4, atol=1e-3)

    # Force the monolithic fused round, then the assembled circular
    # layout, then the legacy layout, by mocking the preferred
    # builders away and clearing the runner cache; results must match
    # bitwise at each downgrade.
    import pytest
    from parallel_heat_tpu import solver as slv

    mp = pytest.MonkeyPatch()
    try:
        mp.setattr(ps, "_build_band_fix_2d", lambda *a, **k: None)
        slv._build_runner.cache_clear()
        assert ps.pick_block_temporal_2d_deferred(
            cfg, AXIS_NAMES[:2]) is None
        uniform = solve(cfg).to_numpy()
        mp.setattr(ps, "_build_temporal_block_uniform",
                   lambda *a, **k: None)
        slv._build_runner.cache_clear()
        kind, _, _ = ps.pick_block_temporal_2d(cfg, AXIS_NAMES[:2])
        assert kind == "G-fuse"
        fused = solve(cfg).to_numpy()
        np.testing.assert_array_equal(uniform, fused)
        mp.setattr(ps, "_build_temporal_block_fused",
                   lambda *a, **k: None)
        slv._build_runner.cache_clear()
        kind, _, _ = ps.pick_block_temporal_2d(cfg, AXIS_NAMES[:2])
        assert kind == "G-circ"
        circ = solve(cfg).to_numpy()
        mp.setattr(ps, "_build_temporal_block_circular",
                   lambda *a, **k: None)
        slv._build_runner.cache_clear()
        kind, _, _ = ps.pick_block_temporal_2d(cfg, AXIS_NAMES[:2])
        assert kind == "G"
        legacy = solve(cfg).to_numpy()
    finally:
        mp.undo()
        slv._build_runner.cache_clear()
    np.testing.assert_array_equal(overlapped, fused)
    np.testing.assert_array_equal(fused, circ)
    np.testing.assert_array_equal(circ, legacy)


def _flat_jaxpr_levels(jaxpr, out=None):
    """All jaxpr levels reachable from ``jaxpr`` (params recursed)."""
    if out is None:
        out = []
    out.append(jaxpr)
    for e in jaxpr.eqns:
        for v in e.params.values():
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns"):
                _flat_jaxpr_levels(inner, out)
    return out


def _ancestor_eqns(jaxpr, eqn):
    """Indices of ``jaxpr.eqns`` the given eqn transitively reads."""
    prod = {}
    for i, e in enumerate(jaxpr.eqns):
        for v in e.outvars:
            prod[v] = i
    anc = set()
    stack = [v for v in eqn.invars if not hasattr(v, "val")]
    while stack:
        v = stack.pop()
        i = prod.get(v)
        if i is None or i in anc:
            continue
        anc.add(i)
        stack.extend(vv for vv in jaxpr.eqns[i].invars
                     if not hasattr(vv, "val"))
    return anc


def test_overlap_bulk_kernel_independent_of_phase2_ppermutes():
    # The whole point of the deferred-band round: the bulk Mosaic call
    # must have NO data path from the second (row strip) ppermute
    # phase, so XLA's scheduler may overlap that collective hop with
    # the bulk compute (the reference's interior-between-Startall-and-
    # Waitall, mpi/...stat.c:160-177). Proven on the traced program:
    # in the shard_map body, the large pallas_call's ancestor set
    # contains no ppermute that itself depends on another ppermute,
    # while the band pallas_call's does.
    import jax
    import jax.numpy as jnp
    from jax import lax

    from parallel_heat_tpu.parallel import temporal as tp
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh
    from parallel_heat_tpu.utils.compat import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    cfg = HeatConfig(nx=32, ny=32, steps=8, backend="pallas",
                     mesh_shape=(2, 2), halo_depth=8)
    mesh = make_heat_mesh((2, 2))
    names = mesh.axis_names

    def local_round(u):
        bidx = tuple(lax.axis_index(n) for n in names)
        kw = dict(mesh_shape=(2, 2), grid_shape=(32, 32),
                  block_index=bidx, cx=0.1, cy=0.1, axis_names=names)
        fn = tp._pallas_round_2d(cfg, kw)
        assert fn is not None
        return fn(u, False)

    f = _shard_map(local_round, mesh=mesh, in_specs=P(*names),
                   out_specs=P(*names), check_vma=False)
    jx = jax.make_jaxpr(f)(jnp.zeros((32, 32), jnp.float32))
    levels = [lv for lv in _flat_jaxpr_levels(jx.jaxpr)
              if any(e.primitive.name == "ppermute" for e in lv.eqns)]
    assert levels, "no ppermutes found in the traced round"
    body = levels[0]
    perms = [i for i, e in enumerate(body.eqns)
             if e.primitive.name == "ppermute"]
    assert len(perms) == 4  # two column shifts + two row-strip shifts
    phase2 = {i for i in perms
              if any(a in perms for a in _ancestor_eqns(body,
                                                        body.eqns[i]))}
    assert len(phase2) == 2  # the row strips depend on the tail
    pallas = [(i, e) for i, e in enumerate(body.eqns)
              if e.primitive.name == "pallas_call"]
    assert len(pallas) == 2  # bulk + band
    # The bulk call consumes (offs, u, tail); the band call also takes
    # the two row-halo strips.
    bulk = min(pallas, key=lambda ie: len(ie[1].invars))
    band = max(pallas, key=lambda ie: len(ie[1].invars))
    assert len(bulk[1].invars) == 3 and len(band[1].invars) == 5
    assert not (phase2 & _ancestor_eqns(body, bulk[1])), \
        "bulk kernel depends on phase-2 ppermutes: no overlap possible"
    assert phase2 & _ancestor_eqns(body, band[1]), \
        "band kernel should be the phase-2 consumer"


def test_halo_overlap_schedules_bitwise_2d():
    # The Overlapped-exchange contract (SEMANTICS.md): phase /
    # overlap schedules of the jnp deep rounds are bitwise the
    # single-device run — fixed with a remainder round, plus bf16
    # storage rounding — on a mesh with both axes sharded.
    for kw in (dict(steps=13), dict(steps=17, dtype="bfloat16")):
        want = _want(32, 32, **kw)
        for sched in ("phase", "overlap"):
            got = solve(HeatConfig(nx=32, ny=32, backend="jnp",
                                   mesh_shape=(2, 4), halo_depth=4,
                                   halo_overlap=sched, **kw)).to_numpy()
            np.testing.assert_array_equal(got, want, err_msg=sched)


def test_halo_overlap_schedules_bitwise_2d_converge():
    kw = dict(steps=400, converge=True, check_interval=20, eps=1e-6)
    want = solve(HeatConfig(nx=24, ny=24, backend="jnp", **kw))
    for sched in ("phase", "overlap"):
        got = solve(HeatConfig(nx=24, ny=24, backend="jnp",
                               mesh_shape=(2, 2), halo_depth=8,
                               halo_overlap=sched, **kw))
        assert got.steps_run == want.steps_run
        assert got.residual == want.residual
        np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_halo_overlap_schedules_bitwise_3d():
    want = solve(HeatConfig(nx=12, ny=12, nz=16, steps=7,
                            backend="jnp")).to_numpy()
    for mesh in ((2, 2, 2), (2, 1, 2)):
        for sched in ("phase", "overlap"):
            got = solve(HeatConfig(nx=12, ny=12, nz=16, steps=7,
                                   backend="jnp", mesh_shape=mesh,
                                   halo_depth=3,
                                   halo_overlap=sched)).to_numpy()
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"{mesh} {sched}")


def test_halo_overlap_short_block_falls_back_bitwise():
    # b0 < 2k: no two disjoint k-bands — the deferred round must fall
    # back to the monolithic one (not slice garbage) and stay bitwise.
    want = _want(16, 16, steps=13)
    got = solve(HeatConfig(nx=16, ny=16, steps=13, backend="jnp",
                           mesh_shape=(2, 2), halo_depth=8,
                           halo_overlap="overlap")).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_pallas_pipeline_schedule_bitwise():
    # Kernel-G schedule triple: phase-separated, deferred-band, and
    # pipelined double-buffered rounds are bitwise identical (the
    # pipelined round's exchanged edge strips are the band/panel
    # recomputation of exactly the bytes the other schedules slice
    # from the assembled state), fixed AND converge.
    kwp = dict(nx=32, ny=32, backend="pallas", mesh_shape=(2, 2),
               halo_depth=8)
    for kw in (dict(steps=24),
               dict(steps=200, converge=True, check_interval=20,
                    eps=1e-6)):
        outs = {}
        for sched in ("phase", "overlap", "pipeline"):
            r = solve(HeatConfig(**kwp, halo_overlap=sched, **kw))
            outs[sched] = r
        assert (outs["phase"].steps_run == outs["overlap"].steps_run
                == outs["pipeline"].steps_run)
        np.testing.assert_array_equal(outs["phase"].to_numpy(),
                                      outs["overlap"].to_numpy())
        np.testing.assert_array_equal(outs["overlap"].to_numpy(),
                                      outs["pipeline"].to_numpy())
    # and the oracle stays within the usual reassociation tolerance
    want = _want(32, 32, steps=24)
    np.testing.assert_allclose(
        solve(HeatConfig(**kwp, halo_overlap="pipeline",
                         steps=24)).to_numpy(),
        want, rtol=1e-4, atol=1e-3)


@pytest.mark.slow
def test_pallas_pipeline_bf16_and_f32chunk_inert():
    # bf16 pipelined round (K=16, the other sublane depth): bitwise
    # its phase-separated twin.
    # slow (tier-1 wall budget, round 15): a second-dtype instance of
    # the schedule-bitwise contract test_pallas_pipeline_schedule_
    # bitwise pins in tier-1 at f32, plus inertness cross-checks the
    # resolution-matrix test already covers.
    kwp = dict(nx=64, ny=64, steps=17, dtype="bfloat16",
               backend="pallas", mesh_shape=(2, 2), halo_depth=16)
    a = solve(HeatConfig(**kwp, halo_overlap="phase")).to_numpy()
    b = solve(HeatConfig(**kwp, halo_overlap="pipeline")).to_numpy()
    np.testing.assert_array_equal(a, b)
    # f32chunk is single-device by contract, so the schedule flag is
    # inert there — every spelling validates and produces identical
    # bits (the f32chunk rounding chains untouched).
    kwf = dict(nx=32, ny=32, steps=37, dtype="bfloat16",
               accumulate="f32chunk", backend="jnp")
    want = solve(HeatConfig(**kwf)).to_numpy()
    for sched in ("phase", "overlap", "pipeline"):
        got = solve(HeatConfig(**kwf, halo_overlap=sched)).to_numpy()
        np.testing.assert_array_equal(got, want, err_msg=sched)


def test_resolve_halo_overlap_matrix():
    """Pin the halo_overlap=None/'auto' resolution: pipeline exactly
    when the kernel-G pipelined round exists (pallas, 2D, sharded y
    axis, geometry admits) and the ICI model prices a win; overlap
    everywhere else; explicit values win."""
    from parallel_heat_tpu.parallel.temporal import resolve_halo_overlap

    r = resolve_halo_overlap
    # pallas + both-axes mesh + admitting geometry -> pipeline
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=8),
             "pallas") == "pipeline"
    # jnp rounds: the deferred schedule (no pipelined jnp round)
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=4),
             "jnp") == "overlap"
    # y axis unsharded: phase 1 exchanges nothing — nothing to hide
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(4, 1), halo_depth=8),
             "pallas") == "overlap"
    # explicit always wins
    assert r(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2), halo_depth=8,
                        halo_overlap="phase"), "pallas") == "phase"
    # depth-1 / unsharded: inert, resolves to overlap
    assert r(HeatConfig(nx=64, ny=64, halo_depth=1), "pallas") \
        == "overlap"


def test_explain_reports_halo_overlap_schedule():
    from parallel_heat_tpu.solver import explain

    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="pallas"))
    assert out["halo_overlap"] == "pipeline (auto)"
    assert "pipelined double-buffered edge strips" in out["path"]
    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="pallas", halo_overlap="overlap"))
    assert out["halo_overlap"] == "overlap"
    assert "deferred N/S bands" in out["path"]
    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="pallas", halo_overlap="phase"))
    assert "deferred" not in out["path"] \
        and "pipelined" not in out["path"]
    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="jnp", halo_depth=4))
    assert "deferred bands" in out["path"]
    # depth-1 sharded configs carry no schedule row (inert there)
    out = explain(HeatConfig(nx=64, ny=64, mesh_shape=(2, 2),
                             backend="jnp"))
    assert "halo_overlap" not in out


def test_overlap_bulk_independent_of_phase2_ppermutes_jnp():
    # The jnp deferred round's dataflow proof (the pallas twin lives
    # in test_overlap_bulk_kernel_independent_of_phase2_ppermutes):
    # the bulk window's K steps must have NO ancestor among the
    # phase-2 (row strip) ppermutes — those are exactly the ppermutes
    # that depend on another ppermute — while the band windows must
    # consume them.
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from parallel_heat_tpu.parallel import temporal as tp
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh
    from parallel_heat_tpu.utils.compat import shard_map as _shard_map

    mesh = make_heat_mesh((2, 2))
    names = mesh.axis_names

    def local_round(u):
        bidx = (lax.axis_index("x"), lax.axis_index("y"))
        return tp.block_multistep_2d(
            u, 4, mesh_shape=(2, 2), grid_shape=(32, 32),
            block_index=bidx, cx=0.1, cy=0.1, axis_names=names,
            overlap=True)

    f = _shard_map(local_round, mesh=mesh, in_specs=P(*names),
                   out_specs=P(*names))
    jx = jax.make_jaxpr(f)(jnp.zeros((32, 32), jnp.float32))
    levels = [lv for lv in _flat_jaxpr_levels(jx.jaxpr)
              if any(e.primitive.name == "ppermute" for e in lv.eqns)]
    assert levels, "no ppermutes found in the traced round"
    body = levels[0]
    perms = [i for i, e in enumerate(body.eqns)
             if e.primitive.name == "ppermute"]
    assert len(perms) == 4
    phase2 = {i for i in perms
              if any(a in perms for a in _ancestor_eqns(body,
                                                        body.eqns[i]))}
    assert len(phase2) == 2  # the row strips depend on the tail
    # The final concatenate assembles (top band, bulk, bottom band);
    # its middle operand is the bulk slice (the lead assembly is also
    # 3-ary but wider than the block, so the shape filter is exact).
    concats = [e for e in body.eqns
               if e.primitive.name == "concatenate"
               and len(e.invars) == 3
               and e.outvars[0].aval.shape == (16, 16)]
    assert concats, "deferred round's core assembly not found"
    asm = concats[-1]
    prod = {v: i for i, e in enumerate(body.eqns) for v in e.outvars}
    bulk_eqn = body.eqns[prod[asm.invars[1]]]
    band_eqn_t = body.eqns[prod[asm.invars[0]]]
    assert not (phase2 & _ancestor_eqns(body, bulk_eqn)), \
        "bulk window depends on phase-2 ppermutes: no overlap possible"
    assert phase2 & _ancestor_eqns(body, band_eqn_t), \
        "band window should be the phase-2 consumer"


def test_halo_overlap_observation_fields_share_compiled_programs():
    """The acceptance pin: flipping observation-only fields on an
    overlapped-schedule config causes ZERO new _build_runner entries
    (the guard/diag/pipeline strip applies before the schedule-keyed
    lookup), and the observed grids stay bitwise."""
    from parallel_heat_tpu import solver as slv
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=32, ny=32, steps=24, backend="jnp",
                     mesh_shape=(2, 2), halo_depth=4,
                     halo_overlap="overlap")
    base = None
    for base in solve_stream(cfg, chunk_steps=12):
        base_grid = base.to_numpy()
    misses0 = slv._build_runner.cache_info().misses
    obs = cfg.replace(guard_interval=6, diag_interval=12,
                      pipeline_depth=1)
    last = None
    for last in solve_stream(obs, chunk_steps=12):
        last_grid = last.to_numpy()
    assert slv._build_runner.cache_info().misses == misses0, \
        "observation-only fields forked the overlapped-schedule cache"
    assert last.finite is True and last.diagnostics is not None
    np.testing.assert_array_equal(last_grid, base_grid)


def test_kernel_g_circular_diverging_boundary_exact():
    import warnings

    kw = dict(nx=32, ny=32, steps=64, cx=0.9, cy=0.9)
    ini = solve(HeatConfig(steps=0, nx=32, ny=32, cx=0.9,
                           cy=0.9)).to_numpy()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        out = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2),
                               halo_depth=8, **kw)).to_numpy()
    assert not np.all(np.isfinite(out))
    for sl in [np.s_[0], np.s_[-1], np.s_[:, 0], np.s_[:, -1]]:
        np.testing.assert_array_equal(out[sl], ini[sl])
