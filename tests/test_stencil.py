import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from parallel_heat_tpu.ops import (
    step_2d,
    step_2d_residual,
    step_3d,
    stencil_interior_2d,
)


@pytest.mark.parametrize("shape", [(3, 3), (5, 7), (16, 12), (33, 9)])
def test_step_matches_oracle(shape):
    rng = np.random.default_rng(0)
    u = rng.standard_normal(shape).astype(np.float32) * 10
    got = np.asarray(step_2d(jnp.asarray(u), 0.1, 0.1))
    want = oracle.step(u)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


def test_boundary_never_written():
    rng = np.random.default_rng(1)
    u = rng.standard_normal((10, 14)).astype(np.float32)
    v = np.asarray(step_2d(jnp.asarray(u), 0.1, 0.1))
    np.testing.assert_array_equal(v[0, :], u[0, :])
    np.testing.assert_array_equal(v[-1, :], u[-1, :])
    np.testing.assert_array_equal(v[:, 0], u[:, 0])
    np.testing.assert_array_equal(v[:, -1], u[:, -1])


def test_uniform_grid_is_fixed_point():
    u = jnp.full((9, 9), 3.5, dtype=jnp.float32)
    v = step_2d(u, 0.1, 0.1)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(u))


def test_residual_matches_direct_diff():
    rng = np.random.default_rng(2)
    u = rng.standard_normal((12, 12)).astype(np.float32)
    v, res = step_2d_residual(jnp.asarray(u), 0.1, 0.1)
    want = np.max(np.abs(np.asarray(v) - u))
    np.testing.assert_allclose(float(res), want, rtol=1e-6)


def test_residual_zero_on_fixed_point():
    u = jnp.zeros((8, 8), dtype=jnp.float32)
    _, res = step_2d_residual(u, 0.1, 0.1)
    assert float(res) == 0.0


def test_interior_op_shape():
    u = jnp.zeros((10, 20))
    assert stencil_interior_2d(u, 0.1, 0.1).shape == (8, 18)


def test_step_3d_matches_oracle():
    rng = np.random.default_rng(3)
    u = rng.standard_normal((6, 7, 8)).astype(np.float32)
    got = np.asarray(step_3d(jnp.asarray(u), 0.1, 0.1, 0.1))
    want = oracle.step3d(u)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


def test_bf16_storage_f32_accumulation():
    rng = np.random.default_rng(4)
    u32 = rng.standard_normal((16, 16)).astype(np.float32)
    ub = jnp.asarray(u32).astype(jnp.bfloat16)
    v = step_2d(ub, 0.1, 0.1)
    assert v.dtype == jnp.bfloat16
    want = oracle.step(np.asarray(ub.astype(jnp.float32)))
    got = np.asarray(v.astype(jnp.float32))
    # bf16 storage rounding only — accumulation must have been f32.
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)
