import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=7, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, res.steps_run, cfg)
    grid, step, saved = load_checkpoint(p)
    np.testing.assert_array_equal(grid, res.to_numpy())
    assert step == 7
    assert saved.shape == (16, 12)


def test_geometry_mismatch_rejected(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    with pytest.raises(ValueError, match="checkpoint grid"):
        load_checkpoint(p, HeatConfig(nx=8, ny=8))


def test_resume_continues_exactly(tmp_path):
    cfg30 = HeatConfig(nx=16, ny=16, steps=30, backend="jnp")
    mid = solve(cfg30)
    p = tmp_path / "c.npz"
    save_checkpoint(p, mid.grid, 30, cfg30)
    grid, step, _ = load_checkpoint(p)
    rest = solve(HeatConfig(nx=16, ny=16, steps=20, backend="jnp"),
                 initial=grid)
    direct = solve(HeatConfig(nx=16, ny=16, steps=50, backend="jnp"))
    np.testing.assert_array_equal(rest.to_numpy(), direct.to_numpy())


def test_solve_stream_matches_unchunked():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=16, ny=16, steps=50, backend="jnp")
    direct = solve(cfg)
    seen = []
    last = None
    for last in solve_stream(cfg, chunk_steps=20):
        seen.append((last.steps_run, last.to_numpy()))
    assert [s for s, _ in seen] == [20, 40, 50]
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_converge_stops_early():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, backend="jnp")
    direct = solve(cfg)
    results = list(solve_stream(cfg, chunk_steps=500))
    last = results[-1]
    assert last.converged
    assert last.steps_run == direct.steps_run
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_chunk_rounds_to_check_interval():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=100, converge=True,
                     check_interval=20, backend="jnp")
    # chunk 30 -> rounded to 40; schedule stays identical to unchunked
    steps_seen = [r.steps_run for r in solve_stream(cfg, chunk_steps=30)]
    direct = solve(cfg)
    assert steps_seen[-1] == direct.steps_run


def test_solve_stream_rejects_bad_chunk():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10, backend="jnp")
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=0))
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=-8))


def test_save_checkpoint_atomic_no_temp_left(tmp_path):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    for step in (1, 2, 3):  # rolling overwrite, like --checkpoint-every
        written = save_checkpoint(p, res.grid, step, cfg)
    assert written == str(p)
    _, step, _ = load_checkpoint(p)
    assert step == 3
    assert list(tmp_path.iterdir()) == [p]  # no temp debris


def test_save_checkpoint_failure_preserves_previous(tmp_path, monkeypatch):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    save_checkpoint(p, res.grid, 1, cfg)

    def boom(path, **kw):
        # simulate a crash mid-write: truncated tmp file then failure
        open(path, "wb").write(b"torn")
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)  # the default (uncompressed) path
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg)
    monkeypatch.setattr(np, "savez_compressed", boom)
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg, compress=True)
    monkeypatch.undo()
    grid, step, _ = load_checkpoint(p)  # previous snapshot intact
    assert step == 1
    assert list(tmp_path.iterdir()) == [p]  # tmp debris removed


def test_elastic_resume_across_mesh_shapes(tmp_path):
    # "Elastic recovery": a checkpoint taken on one mesh resumes onto a
    # different mesh (or a single device) — the grid is host-portable
    # and re-sharded by GSPMD at dispatch. All variants must agree
    # bitwise with an uninterrupted single-device run (jnp backend).
    base = dict(nx=32, ny=32, backend="jnp")
    mid = solve(HeatConfig(steps=30, mesh_shape=(2, 2), **base))
    p = tmp_path / "elastic.npz"
    save_checkpoint(p, mid.to_numpy(), 30, HeatConfig(steps=30, **base))
    grid, step, _ = load_checkpoint(p)
    assert step == 30
    want = solve(HeatConfig(steps=50, **base)).to_numpy()
    for mesh in (None, (4, 2), (1, 8), (2, 2)):
        rest = solve(HeatConfig(steps=20, mesh_shape=mesh, **base),
                     initial=grid)
        np.testing.assert_array_equal(rest.to_numpy(), want,
                                      err_msg=f"mesh={mesh}")
    # and onto a deep-halo temporal run
    rest = solve(HeatConfig(steps=20, mesh_shape=(2, 2), halo_depth=4,
                            **base), initial=grid)
    np.testing.assert_array_equal(rest.to_numpy(), want)


# ---------------------------------------------------------------------------
# Per-shard layout (no-host-gather checkpoints)
# ---------------------------------------------------------------------------

def test_sharded_checkpoint_roundtrip_resume(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4))
    half = solve(HeatConfig(steps=20, **kw))
    d = save_checkpoint(tmp_path / "ck", half.grid, 20,
                        HeatConfig(steps=40, **kw), layout="sharded")
    assert d.endswith(".ckpt") and os.path.isdir(d)
    files = sorted(os.listdir(d))
    assert "manifest.json" in files
    assert any(f.startswith("shards_") for f in files)

    grid, step, saved = load_checkpoint(d, HeatConfig(steps=40, **kw))
    assert step == 20
    # fast path: device-resident sharded array, not a host ndarray
    import jax
    assert isinstance(grid, jax.Array)
    assert len(grid.sharding.device_set) == 8
    rest = solve(HeatConfig(steps=20, **kw), initial=grid)
    full = solve(HeatConfig(steps=40, **kw))
    np.testing.assert_array_equal(rest.to_numpy(), full.to_numpy())


def test_sharded_checkpoint_resolves_from_npz_stem(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=4, **kw))
    save_checkpoint(tmp_path / "ck.npz", res.grid, 4,
                    HeatConfig(steps=4, **kw), layout="sharded")
    # pointing --resume at the .npz name still finds the .ckpt dir
    grid, step, _ = load_checkpoint(tmp_path / "ck.npz")
    assert step == 4
    np.testing.assert_array_equal(np.asarray(grid), res.to_numpy())


def test_sharded_auto_threshold(tmp_path, monkeypatch):
    import os

    from parallel_heat_tpu.utils import checkpoint as cp

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=2, **kw))
    # small grid: auto stays gathered
    p = cp.save_checkpoint(tmp_path / "small", res.grid, 2,
                           HeatConfig(steps=2, **kw))
    assert p.endswith(".npz") and os.path.isfile(p)
    # same grid with the threshold forced down: auto shards
    monkeypatch.setattr(cp, "_SHARD_THRESHOLD_BYTES", 0)
    p2 = cp.save_checkpoint(tmp_path / "small", res.grid, 2,
                            HeatConfig(steps=2, **kw))
    assert p2.endswith(".ckpt") and os.path.isdir(p2)
    # the sharded save removed the stale gathered file so loads can
    # never resurrect it
    assert not os.path.exists(p)


def test_sharded_checkpoint_host_assembly_fallback(tmp_path):
    import json
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=6, **kw))
    d = save_checkpoint(tmp_path / "ck", res.grid, 6,
                        HeatConfig(steps=6, **kw), layout="sharded")
    # Simulate a topology change: claim the snapshot came from a mesh
    # needing more devices than exist -> single-process host assembly.
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mesh_shape"] = [16, 16]
    json.dump(man, open(mpath, "w"))
    grid, step, _ = load_checkpoint(d)
    assert isinstance(grid, np.ndarray)
    assert step == 6
    np.testing.assert_array_equal(grid, res.to_numpy())


def test_sharded_checkpoint_generations_pruned(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=8, **kw)
    a = solve(HeatConfig(steps=4, **kw))
    b = solve(HeatConfig(steps=8, **kw))
    d = save_checkpoint(tmp_path / "roll", a.grid, 4, cfg,
                        layout="sharded")
    d = save_checkpoint(tmp_path / "roll", b.grid, 8, cfg,
                        layout="sharded")
    shard_files = [f for f in os.listdir(d) if f.startswith("shards_")]
    assert all("s000000000008" in f for f in shard_files), shard_files
    grid, step, _ = load_checkpoint(d)
    assert step == 8
    np.testing.assert_array_equal(np.asarray(grid), b.to_numpy())


def test_cli_sharded_checkpoint_roundtrip(tmp_path):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    ck = tmp_path / "ck"
    assert main(["--nx", "16", "--ny", "16", "--steps", "30",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--checkpoint", str(ck),
                 "--checkpoint-layout", "sharded", "--quiet"]) == 0
    out = tmp_path / "resumed.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--resume", str(ck) + ".ckpt",
                 "--out", str(out), "--quiet"]) == 0
    out2 = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--out", str(out2), "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(out2))


def test_sharded_loader_ignores_orphan_temps_and_prunes(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    # A crashed writer's orphan temp must be invisible to loads...
    orphan = os.path.join(d, ".tmp-999-shards_s000000000004_p00000.npz")
    with open(orphan, "wb") as f:
        f.write(b"torn garbage")
    grid, step, _ = load_checkpoint(d)
    np.testing.assert_array_equal(np.asarray(grid), res.to_numpy())
    # ...and the next save's prune removes it.
    save_checkpoint(tmp_path / "ck", res.grid, 8, cfg, layout="sharded")
    assert not os.path.exists(orphan)


def test_sharded_fastpath_falls_back_on_index_mismatch(tmp_path,
                                                       monkeypatch):
    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    # Simulate the load-time device->block assignment moving (topology
    # reorder between runs): the rebuilt mesh permutes devices, so the
    # recomputed index map disagrees with the manifest. The fast path
    # must detect this and fall back to host assembly (which trusts
    # only the manifest) instead of silently placing blocks by id —
    # and the resumed content must still be exact.
    import jax

    from parallel_heat_tpu.parallel import mesh as mesh_mod

    real = mesh_mod.make_heat_mesh

    def permuted(mesh_shape, devices=None):
        devs = list(reversed(jax.devices()))[:4]
        return real(mesh_shape, devices=devs)

    monkeypatch.setattr(mesh_mod, "make_heat_mesh", permuted)
    grid, step, _ = load_checkpoint(d)
    assert isinstance(grid, np.ndarray)  # fell back, no silent misplace
    np.testing.assert_array_equal(grid, res.to_numpy())


def test_gathered_layout_refuses_unreachable(monkeypatch, tmp_path):
    from parallel_heat_tpu.utils import checkpoint as cp

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=2, **kw)
    res = solve(cfg)

    class FakeGrid:
        is_fully_addressable = False
        shape = res.grid.shape
        size = res.grid.size
        dtype = np.dtype("float32")
        sharding = res.grid.sharding
        addressable_shards = res.grid.addressable_shards

    import pytest
    with pytest.raises(ValueError, match="non-addressable"):
        cp.save_checkpoint(tmp_path / "x", FakeGrid(), 2, cfg,
                           layout="gathered")
    # auto on the same grid routes to sharded regardless of size
    p = cp.save_checkpoint(tmp_path / "x", FakeGrid(), 2, cfg)
    assert p.endswith(".ckpt")
