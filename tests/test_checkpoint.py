import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=7, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, res.steps_run, cfg)
    grid, step, saved = load_checkpoint(p)
    np.testing.assert_array_equal(grid, res.to_numpy())
    assert step == 7
    assert saved.shape == (16, 12)


def test_geometry_mismatch_rejected(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    with pytest.raises(ValueError, match="checkpoint grid"):
        load_checkpoint(p, HeatConfig(nx=8, ny=8))


def test_resume_continues_exactly(tmp_path):
    cfg30 = HeatConfig(nx=16, ny=16, steps=30, backend="jnp")
    mid = solve(cfg30)
    p = tmp_path / "c.npz"
    save_checkpoint(p, mid.grid, 30, cfg30)
    grid, step, _ = load_checkpoint(p)
    rest = solve(HeatConfig(nx=16, ny=16, steps=20, backend="jnp"),
                 initial=grid)
    direct = solve(HeatConfig(nx=16, ny=16, steps=50, backend="jnp"))
    np.testing.assert_array_equal(rest.to_numpy(), direct.to_numpy())


def test_solve_stream_matches_unchunked():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=16, ny=16, steps=50, backend="jnp")
    direct = solve(cfg)
    seen = []
    last = None
    for last in solve_stream(cfg, chunk_steps=20):
        seen.append((last.steps_run, last.to_numpy()))
    assert [s for s, _ in seen] == [20, 40, 50]
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_converge_stops_early():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, backend="jnp")
    direct = solve(cfg)
    results = list(solve_stream(cfg, chunk_steps=500))
    last = results[-1]
    assert last.converged
    assert last.steps_run == direct.steps_run
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_chunk_rounds_to_check_interval():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=100, converge=True,
                     check_interval=20, backend="jnp")
    # chunk 30 -> rounded to 40; schedule stays identical to unchunked
    steps_seen = [r.steps_run for r in solve_stream(cfg, chunk_steps=30)]
    direct = solve(cfg)
    assert steps_seen[-1] == direct.steps_run


def test_solve_stream_rejects_bad_chunk():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10, backend="jnp")
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=0))
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=-8))


def test_save_checkpoint_atomic_no_temp_left(tmp_path):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    for step in (1, 2, 3):  # rolling overwrite, like --checkpoint-every
        written = save_checkpoint(p, res.grid, step, cfg)
    assert written == str(p)
    _, step, _ = load_checkpoint(p)
    assert step == 3
    assert list(tmp_path.iterdir()) == [p]  # no temp debris


def test_save_checkpoint_failure_preserves_previous(tmp_path, monkeypatch):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    save_checkpoint(p, res.grid, 1, cfg)

    def boom(path, **kw):
        # simulate a crash mid-write: truncated tmp file then failure
        open(path, "wb").write(b"torn")
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)  # the default (uncompressed) path
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg)
    monkeypatch.setattr(np, "savez_compressed", boom)
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg, compress=True)
    monkeypatch.undo()
    grid, step, _ = load_checkpoint(p)  # previous snapshot intact
    assert step == 1
    assert list(tmp_path.iterdir()) == [p]  # tmp debris removed


def test_elastic_resume_across_mesh_shapes(tmp_path):
    # "Elastic recovery": a checkpoint taken on one mesh resumes onto a
    # different mesh (or a single device) — the grid is host-portable
    # and re-sharded by GSPMD at dispatch. All variants must agree
    # bitwise with an uninterrupted single-device run (jnp backend).
    base = dict(nx=32, ny=32, backend="jnp")
    mid = solve(HeatConfig(steps=30, mesh_shape=(2, 2), **base))
    p = tmp_path / "elastic.npz"
    save_checkpoint(p, mid.to_numpy(), 30, HeatConfig(steps=30, **base))
    grid, step, _ = load_checkpoint(p)
    assert step == 30
    want = solve(HeatConfig(steps=50, **base)).to_numpy()
    for mesh in (None, (4, 2), (1, 8), (2, 2)):
        rest = solve(HeatConfig(steps=20, mesh_shape=mesh, **base),
                     initial=grid)
        np.testing.assert_array_equal(rest.to_numpy(), want,
                                      err_msg=f"mesh={mesh}")
    # and onto a deep-halo temporal run
    rest = solve(HeatConfig(steps=20, mesh_shape=(2, 2), halo_depth=4,
                            **base), initial=grid)
    np.testing.assert_array_equal(rest.to_numpy(), want)
