import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=7, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, res.steps_run, cfg)
    grid, step, saved = load_checkpoint(p)
    np.testing.assert_array_equal(grid, res.to_numpy())
    assert step == 7
    assert saved.shape == (16, 12)


def test_geometry_mismatch_rejected(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    with pytest.raises(ValueError, match="checkpoint grid"):
        load_checkpoint(p, HeatConfig(nx=8, ny=8))


def test_resume_continues_exactly(tmp_path):
    cfg30 = HeatConfig(nx=16, ny=16, steps=30, backend="jnp")
    mid = solve(cfg30)
    p = tmp_path / "c.npz"
    save_checkpoint(p, mid.grid, 30, cfg30)
    grid, step, _ = load_checkpoint(p)
    rest = solve(HeatConfig(nx=16, ny=16, steps=20, backend="jnp"),
                 initial=grid)
    direct = solve(HeatConfig(nx=16, ny=16, steps=50, backend="jnp"))
    np.testing.assert_array_equal(rest.to_numpy(), direct.to_numpy())
