import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=7, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, res.steps_run, cfg)
    grid, step, saved = load_checkpoint(p)
    np.testing.assert_array_equal(grid, res.to_numpy())
    assert step == 7
    assert saved.shape == (16, 12)


def test_geometry_mismatch_rejected(tmp_path):
    cfg = HeatConfig(nx=16, ny=12, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "c.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    with pytest.raises(ValueError, match="checkpoint grid"):
        load_checkpoint(p, HeatConfig(nx=8, ny=8))


def test_resume_continues_exactly(tmp_path):
    cfg30 = HeatConfig(nx=16, ny=16, steps=30, backend="jnp")
    mid = solve(cfg30)
    p = tmp_path / "c.npz"
    save_checkpoint(p, mid.grid, 30, cfg30)
    grid, step, _ = load_checkpoint(p)
    rest = solve(HeatConfig(nx=16, ny=16, steps=20, backend="jnp"),
                 initial=grid)
    direct = solve(HeatConfig(nx=16, ny=16, steps=50, backend="jnp"))
    np.testing.assert_array_equal(rest.to_numpy(), direct.to_numpy())


def test_solve_stream_matches_unchunked():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=16, ny=16, steps=50, backend="jnp")
    direct = solve(cfg)
    seen = []
    last = None
    for last in solve_stream(cfg, chunk_steps=20):
        seen.append((last.steps_run, last.to_numpy()))
    assert [s for s, _ in seen] == [20, 40, 50]
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_converge_stops_early():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, backend="jnp")
    direct = solve(cfg)
    results = list(solve_stream(cfg, chunk_steps=500))
    last = results[-1]
    assert last.converged
    assert last.steps_run == direct.steps_run
    np.testing.assert_array_equal(last.to_numpy(), direct.to_numpy())


def test_solve_stream_chunk_rounds_to_check_interval():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=100, converge=True,
                     check_interval=20, backend="jnp")
    # chunk 30 -> rounded to 40; schedule stays identical to unchunked
    steps_seen = [r.steps_run for r in solve_stream(cfg, chunk_steps=30)]
    direct = solve(cfg)
    assert steps_seen[-1] == direct.steps_run


def test_solve_stream_rejects_bad_chunk():
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=12, ny=12, steps=10, backend="jnp")
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=0))
    with pytest.raises(ValueError, match="chunk_steps"):
        next(solve_stream(cfg, chunk_steps=-8))


def test_save_checkpoint_atomic_no_temp_left(tmp_path):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    for step in (1, 2, 3):  # rolling overwrite, like --checkpoint-every
        written = save_checkpoint(p, res.grid, step, cfg)
    assert written == str(p)
    _, step, _ = load_checkpoint(p)
    assert step == 3
    assert list(tmp_path.iterdir()) == [p]  # no temp debris


def test_save_checkpoint_failure_preserves_previous(tmp_path, monkeypatch):
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    save_checkpoint(p, res.grid, 1, cfg)

    def boom(path, **kw):
        # simulate a crash mid-write: truncated tmp file then failure
        open(path, "wb").write(b"torn")
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)  # the default (uncompressed) path
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg)
    monkeypatch.setattr(np, "savez_compressed", boom)
    with pytest.raises(OSError):
        save_checkpoint(p, res.grid, 2, cfg, compress=True)
    monkeypatch.undo()
    grid, step, _ = load_checkpoint(p)  # previous snapshot intact
    assert step == 1
    assert list(tmp_path.iterdir()) == [p]  # tmp debris removed


def test_elastic_resume_across_mesh_shapes(tmp_path):
    # "Elastic recovery": a checkpoint taken on one mesh resumes onto a
    # different mesh (or a single device) — the grid is host-portable
    # and re-sharded by GSPMD at dispatch. All variants must agree
    # bitwise with an uninterrupted single-device run (jnp backend).
    base = dict(nx=32, ny=32, backend="jnp")
    mid = solve(HeatConfig(steps=30, mesh_shape=(2, 2), **base))
    p = tmp_path / "elastic.npz"
    save_checkpoint(p, mid.to_numpy(), 30, HeatConfig(steps=30, **base))
    grid, step, _ = load_checkpoint(p)
    assert step == 30
    want = solve(HeatConfig(steps=50, **base)).to_numpy()
    for mesh in (None, (4, 2), (1, 8), (2, 2)):
        rest = solve(HeatConfig(steps=20, mesh_shape=mesh, **base),
                     initial=grid)
        np.testing.assert_array_equal(rest.to_numpy(), want,
                                      err_msg=f"mesh={mesh}")
    # and onto a deep-halo temporal run
    rest = solve(HeatConfig(steps=20, mesh_shape=(2, 2), halo_depth=4,
                            **base), initial=grid)
    np.testing.assert_array_equal(rest.to_numpy(), want)


# ---------------------------------------------------------------------------
# Per-shard layout (no-host-gather checkpoints)
# ---------------------------------------------------------------------------

def test_sharded_checkpoint_roundtrip_resume(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4))
    half = solve(HeatConfig(steps=20, **kw))
    d = save_checkpoint(tmp_path / "ck", half.grid, 20,
                        HeatConfig(steps=40, **kw), layout="sharded")
    assert d.endswith(".ckpt") and os.path.isdir(d)
    files = sorted(os.listdir(d))
    assert "manifest.json" in files
    assert any(f.startswith("shards_") for f in files)

    grid, step, saved = load_checkpoint(d, HeatConfig(steps=40, **kw))
    assert step == 20
    # fast path: device-resident sharded array, not a host ndarray
    import jax
    assert isinstance(grid, jax.Array)
    assert len(grid.sharding.device_set) == 8
    rest = solve(HeatConfig(steps=20, **kw), initial=grid)
    full = solve(HeatConfig(steps=40, **kw))
    np.testing.assert_array_equal(rest.to_numpy(), full.to_numpy())


def test_sharded_checkpoint_resolves_from_npz_stem(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=4, **kw))
    save_checkpoint(tmp_path / "ck.npz", res.grid, 4,
                    HeatConfig(steps=4, **kw), layout="sharded")
    # pointing --resume at the .npz name still finds the .ckpt dir
    grid, step, _ = load_checkpoint(tmp_path / "ck.npz")
    assert step == 4
    np.testing.assert_array_equal(np.asarray(grid), res.to_numpy())


def test_sharded_auto_threshold(tmp_path, monkeypatch):
    import os

    from parallel_heat_tpu.utils import checkpoint as cp

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=2, **kw))
    # small grid: auto stays gathered
    p = cp.save_checkpoint(tmp_path / "small", res.grid, 2,
                           HeatConfig(steps=2, **kw))
    assert p.endswith(".npz") and os.path.isfile(p)
    # same grid with the threshold forced down: auto shards
    monkeypatch.setattr(cp, "_SHARD_THRESHOLD_BYTES", 0)
    p2 = cp.save_checkpoint(tmp_path / "small", res.grid, 2,
                            HeatConfig(steps=2, **kw))
    assert p2.endswith(".ckpt") and os.path.isdir(p2)
    # the sharded save removed the stale gathered file so loads can
    # never resurrect it
    assert not os.path.exists(p)


def test_sharded_checkpoint_host_assembly_fallback(tmp_path):
    import json
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    res = solve(HeatConfig(steps=6, **kw))
    d = save_checkpoint(tmp_path / "ck", res.grid, 6,
                        HeatConfig(steps=6, **kw), layout="sharded")
    # Simulate a topology change: claim the snapshot came from a mesh
    # needing more devices than exist -> single-process host assembly.
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mesh_shape"] = [16, 16]
    json.dump(man, open(mpath, "w"))
    grid, step, _ = load_checkpoint(d)
    assert isinstance(grid, np.ndarray)
    assert step == 6
    np.testing.assert_array_equal(grid, res.to_numpy())


def test_sharded_checkpoint_generations_pruned(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=8, **kw)
    a = solve(HeatConfig(steps=4, **kw))
    b = solve(HeatConfig(steps=8, **kw))
    d = save_checkpoint(tmp_path / "roll", a.grid, 4, cfg,
                        layout="sharded")
    d = save_checkpoint(tmp_path / "roll", b.grid, 8, cfg,
                        layout="sharded")
    shard_files = [f for f in os.listdir(d) if f.startswith("shards_")]
    assert all("s000000000008" in f for f in shard_files), shard_files
    grid, step, _ = load_checkpoint(d)
    assert step == 8
    np.testing.assert_array_equal(np.asarray(grid), b.to_numpy())


def test_cli_sharded_checkpoint_roundtrip(tmp_path):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    ck = tmp_path / "ck"
    assert main(["--nx", "16", "--ny", "16", "--steps", "30",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--checkpoint", str(ck),
                 "--checkpoint-layout", "sharded", "--quiet"]) == 0
    out = tmp_path / "resumed.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--resume", str(ck) + ".ckpt",
                 "--out", str(out), "--quiet"]) == 0
    out2 = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--mesh", "2,4",
                 "--out", str(out2), "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(out2))


def test_sharded_loader_ignores_orphan_temps_and_prunes(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    # A crashed writer's orphan temp must be invisible to loads...
    orphan = os.path.join(d, ".tmp-999-shards_s000000000004_p00000.npz")
    with open(orphan, "wb") as f:
        f.write(b"torn garbage")
    grid, step, _ = load_checkpoint(d)
    np.testing.assert_array_equal(np.asarray(grid), res.to_numpy())
    # ...and the next save's prune removes it.
    save_checkpoint(tmp_path / "ck", res.grid, 8, cfg, layout="sharded")
    assert not os.path.exists(orphan)


def test_sharded_fastpath_falls_back_on_index_mismatch(tmp_path,
                                                       monkeypatch):
    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    # Simulate the load-time device->block assignment moving (topology
    # reorder between runs): the rebuilt mesh permutes devices, so the
    # recomputed index map disagrees with the manifest. The fast path
    # must detect this and fall back to host assembly (which trusts
    # only the manifest) instead of silently placing blocks by id —
    # and the resumed content must still be exact.
    import jax

    from parallel_heat_tpu.parallel import mesh as mesh_mod

    real = mesh_mod.make_heat_mesh

    def permuted(mesh_shape, devices=None):
        devs = list(reversed(jax.devices()))[:4]
        return real(mesh_shape, devices=devs)

    monkeypatch.setattr(mesh_mod, "make_heat_mesh", permuted)
    grid, step, _ = load_checkpoint(d)
    assert isinstance(grid, np.ndarray)  # fell back, no silent misplace
    np.testing.assert_array_equal(grid, res.to_numpy())


def test_sharded_crash_between_shards_and_manifest_keeps_previous(
        tmp_path, monkeypatch):
    # The preemption-safe-by-construction claim, now actually tested:
    # kill the save AFTER the new generation's shard files land but
    # BEFORE its manifest replaces the old one — the previous
    # generation must load back bit-exactly.
    import os

    from parallel_heat_tpu.utils import checkpoint as cp

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=8, **kw)
    a = solve(HeatConfig(steps=4, **kw))
    b = solve(HeatConfig(steps=8, **kw))
    d = cp.save_checkpoint(tmp_path / "ck", a.grid, 4, cfg,
                           layout="sharded")

    real = cp._fsync_replace

    def crash_on_manifest(tmp, dst):
        if os.path.basename(dst) == "manifest.json":
            raise OSError("killed between shard write and manifest write")
        return real(tmp, dst)

    monkeypatch.setattr(cp, "_fsync_replace", crash_on_manifest)
    with pytest.raises(OSError):
        cp.save_checkpoint(tmp_path / "ck", b.grid, 8, cfg,
                           layout="sharded")
    monkeypatch.undo()
    # new-generation shard files exist, but the manifest still names
    # generation 4 — the load must recover it bit-exactly
    files = sorted(os.listdir(d))
    assert any("s000000000008" in f for f in files)
    grid, step, _ = cp.load_checkpoint(d)
    assert step == 4
    np.testing.assert_array_equal(np.asarray(grid), a.to_numpy())
    # and the next COMPLETE save prunes the orphaned gen-8 shards of
    # the crashed attempt along with everything else stale
    cp.save_checkpoint(tmp_path / "ck", b.grid, 8, cfg, layout="sharded")
    grid, step, _ = cp.load_checkpoint(d)
    assert step == 8
    np.testing.assert_array_equal(np.asarray(grid), b.to_numpy())


def test_gathered_kill_leaves_orphan_tmp_that_next_save_prunes(tmp_path):
    # A SIGKILL mid-gathered-write cannot run `finally` cleanup: it
    # leaves a pid-named temp next to the rolling file. The destination
    # (written only by atomic rename) must still load the previous
    # snapshot, and the next save must prune the orphan.
    import os

    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "roll.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    # pid 4999999 exceeds the default pid_max, so the liveness probe
    # (temps of LIVE pids are concurrent writers, not orphans) always
    # classifies this one as dead
    orphan = tmp_path / "roll.npz.tmp-4999999.npz"
    orphan.write_bytes(b"torn garbage from a SIGKILLed writer")
    grid, step, _ = load_checkpoint(p)  # untouched by the orphan
    assert step == 1
    np.testing.assert_array_equal(grid, res.to_numpy())
    save_checkpoint(p, res.grid, 2, cfg)
    assert not os.path.exists(orphan)
    assert sorted(x.name for x in tmp_path.iterdir()) == ["roll.npz"]


def test_sharded_loader_exact_match_ignores_near_miss_names(tmp_path):
    # The _SHARD_RE_TMPL exact-match guarantee: host assembly must
    # ignore files whose names merely RESEMBLE shard files (backup
    # copies, editor droppings), not read them as data.
    import json
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    for near_miss in ("shards_s000000000004c0001_p00000.npz.bak",
                      "shards_s000000000004c0001_pXXXXX.npz",
                      "shards_s000000000004c0001_p000001.npz"):
        with open(os.path.join(d, near_miss), "wb") as f:
            f.write(b"not a shard file")
    # force host assembly (the path that scans the directory)
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mesh_shape"] = [16, 16]
    json.dump(man, open(mpath, "w"))
    grid, step, _ = load_checkpoint(d)
    assert step == 4
    np.testing.assert_array_equal(grid, res.to_numpy())


def test_generations_save_prune_latest_discovery(tmp_path):
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        generation_paths, latest_checkpoint, save_generation)

    cfg = HeatConfig(nx=8, ny=8, steps=30, backend="jnp")
    res = solve(HeatConfig(nx=8, ny=8, steps=1, backend="jnp"))
    stem = tmp_path / "gen"
    for step in (10, 20, 30):
        written = save_generation(stem, res.grid, step, cfg, keep=2)
        assert os.path.exists(written)
    gens = generation_paths(stem)
    assert [s for s, _ in gens] == [20, 30]  # 10 pruned
    assert latest_checkpoint(stem).endswith(".g000000000030.npz")
    # step-embedded ordering, not mtime: touch the older file, the
    # newest STEP still wins
    os.utime(gens[0][1])
    assert latest_checkpoint(stem) == gens[1][1]
    # every spelling of the family resolves to the same stem
    assert latest_checkpoint(str(stem) + ".npz") == gens[1][1]
    assert latest_checkpoint(gens[0][1]) == gens[1][1]
    # a torn .ckpt generation (no manifest) is invisible to discovery
    os.makedirs(str(stem) + ".g000000000099.ckpt")
    assert latest_checkpoint(stem) == gens[1][1]


def test_save_checkpoint_creates_parent_dirs(tmp_path):
    # `--checkpoint runs/ck` on a fresh host: both layouts must create
    # the missing parent directory instead of dying inside np.savez
    # (found by driving the supervised CLI end to end).
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = save_checkpoint(tmp_path / "a" / "b" / "ck", res.grid, 1, cfg)
    grid, step, _ = load_checkpoint(p)
    assert step == 1
    d = save_checkpoint(tmp_path / "c" / "d" / "ck", res.grid, 1, cfg,
                        layout="sharded")
    grid, step, _ = load_checkpoint(d)
    assert step == 1


def test_latest_checkpoint_falls_back_to_plain_files(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import latest_checkpoint

    assert latest_checkpoint(tmp_path / "nothing") is None
    cfg = HeatConfig(nx=8, ny=8, steps=1, backend="jnp")
    res = solve(cfg)
    p = tmp_path / "single.npz"
    save_checkpoint(p, res.grid, 1, cfg)
    assert latest_checkpoint(tmp_path / "single") == str(p)
    assert latest_checkpoint(p) == str(p)
    d = save_checkpoint(tmp_path / "shardy", res.grid, 1, cfg,
                        layout="sharded")
    assert latest_checkpoint(tmp_path / "shardy") == d


def test_sharded_reshard_on_load_replaces_for_expected_mesh(tmp_path):
    # Satellite: resume a sharded checkpoint onto a topology that
    # cannot rebuild the saved mesh — host assembly must then re-place
    # the grid for the mesh the RESUMING config wants (the
    # _prepare_initial slice-transfer path), returning a device-
    # resident sharded array, not a host ndarray.
    import json
    import os

    import jax

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4))
    cfg = HeatConfig(steps=20, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 20, cfg,
                        layout="sharded")
    # claim the snapshot came from an impossible mesh -> saved-topology
    # fast path cannot run
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mesh_shape"] = [16, 16]
    json.dump(man, open(mpath, "w"))
    want = HeatConfig(steps=40, nx=32, ny=32, backend="jnp",
                      mesh_shape=(2, 2))
    grid, step, _ = load_checkpoint(d, want)
    assert isinstance(grid, jax.Array)
    assert len(grid.sharding.device_set) == 4
    np.testing.assert_array_equal(np.asarray(grid), res.to_numpy())
    # and the resumed solve on the new mesh continues bitwise
    rest = solve(HeatConfig(steps=20, nx=32, ny=32, backend="jnp",
                            mesh_shape=(2, 2)), initial=grid)
    full = solve(HeatConfig(steps=40, **kw))
    np.testing.assert_array_equal(rest.to_numpy(), full.to_numpy())
    # without an expected mesh the host array comes back unchanged
    grid2, _, _ = load_checkpoint(d)
    assert isinstance(grid2, np.ndarray)


def _rewrite_as_foreign_process_ckpt(d, process_count):
    """Re-label a single-process sharded save as one written by
    ``process_count`` processes: split the one shard file into
    per-process files (contiguous device ranges) and patch the
    manifest — the elastic reshard-on-load path trusts only the
    manifest's block indices, which is exactly what this exercises."""
    import json
    import os
    import re
    import zipfile

    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    old = next(f for f in os.listdir(d) if f.startswith("shards_"))
    gen = re.match(r"shards_(.*)_p\d{5}\.npz", old).group(1)
    new_gen = gen[:-4] + f"{process_count:04d}"
    with np.load(os.path.join(d, old)) as z:
        blocks = {k: z[k] for k in z.files}
    os.unlink(os.path.join(d, old))
    ids = sorted(blocks, key=lambda k: int(k[1:]))
    per = len(ids) // process_count
    for proc in range(process_count):
        fname = os.path.join(d, f"shards_{new_gen}_p{proc:05d}.npz")
        with zipfile.ZipFile(fname, "w") as zf:
            for k in ids[proc * per:(proc + 1) * per]:
                with zf.open(f"{k}.npy", "w") as fh:
                    np.lib.format.write_array(fh, blocks[k],
                                              allow_pickle=False)
    man["generation"] = new_gen
    man["process_count"] = process_count
    for n, k in enumerate(ids):
        man["devices"][k[1:]]["process"] = n // per
    json.dump(man, open(mpath, "w"))


def test_elastic_resume_four_process_checkpoint_on_one(tmp_path):
    # ISSUE 10 satellite: resuming a 4-process checkpoint on FEWER
    # processes must be bitwise the uninterrupted run. Here the
    # one-process end of the elastic-degrade path (the 4 -> 2 case
    # rides the real 2-process mp_split_brain chaos cell): a sharded
    # save re-labelled as 4-process loads via host assembly of ALL
    # four shard files, re-places for the resuming mesh, and the
    # continued solve matches bit for bit — on a smaller mesh AND on a
    # single device.
    import jax

    kw = dict(nx=32, ny=32, backend="jnp")
    full = solve(HeatConfig(steps=60, **kw))
    half = solve(HeatConfig(steps=30, **kw, mesh_shape=(2, 4)))
    cfg = HeatConfig(steps=30, **kw, mesh_shape=(2, 4))
    d = save_checkpoint(tmp_path / "ck", half.grid, 30, cfg,
                        layout="sharded")
    _rewrite_as_foreign_process_ckpt(d, 4)
    # smaller mesh (the peer-lost resume command's shape)
    want = HeatConfig(steps=60, **kw, mesh_shape=(2, 2))
    grid, step, _ = load_checkpoint(d, want)
    assert step == 30
    assert isinstance(grid, jax.Array)
    assert len(grid.sharding.device_set) == 4
    rest = solve(want.replace(steps=30), initial=grid)
    np.testing.assert_array_equal(rest.to_numpy(), full.to_numpy())
    # single device (no mesh in the resuming config)
    grid1, step1, _ = load_checkpoint(d, HeatConfig(steps=60, **kw))
    assert step1 == 30
    rest1 = solve(HeatConfig(steps=30, **kw), initial=np.asarray(grid1))
    np.testing.assert_array_equal(rest1.to_numpy(), full.to_numpy())


def test_sharded_incomplete_error_names_process_counts(tmp_path):
    # Satellite: the multi-process mismatch error must be actionable —
    # name the saved vs current process counts and say where the
    # missing shard files live.
    import json
    import os

    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=4, **kw)
    res = solve(cfg)
    d = save_checkpoint(tmp_path / "ck", res.grid, 4, cfg,
                        layout="sharded")
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mesh_shape"] = [16, 16]  # force host assembly
    man["process_count"] = 3      # claim a multi-process save
    json.dump(man, open(mpath, "w"))
    shard = next(f for f in os.listdir(d) if f.startswith("shards_"))
    os.unlink(os.path.join(d, shard))  # the "other host's" file
    with pytest.raises(ValueError) as ei:
        load_checkpoint(d)
    msg = str(ei.value)
    assert "3 process(es)" in msg and "loading on 1" in msg
    assert "copy every shards_" in msg


def test_gathered_layout_refuses_unreachable(monkeypatch, tmp_path):
    from parallel_heat_tpu.utils import checkpoint as cp

    kw = dict(nx=16, ny=16, backend="jnp", mesh_shape=(2, 2))
    cfg = HeatConfig(steps=2, **kw)
    res = solve(cfg)

    class FakeGrid:
        is_fully_addressable = False
        shape = res.grid.shape
        size = res.grid.size
        dtype = np.dtype("float32")
        sharding = res.grid.sharding
        addressable_shards = res.grid.addressable_shards

    import pytest
    with pytest.raises(ValueError, match="non-addressable"):
        cp.save_checkpoint(tmp_path / "x", FakeGrid(), 2, cfg,
                           layout="gathered")
    # auto on the same grid routes to sharded regardless of size
    p = cp.save_checkpoint(tmp_path / "x", FakeGrid(), 2, cfg)
    assert p.endswith(".ckpt")
