import warnings

import numpy as np
import pytest

import oracle
from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.solver import make_initial_grid


def test_zero_steps_returns_initial():
    cfg = HeatConfig(nx=12, ny=10, steps=0, backend="jnp")
    res = solve(cfg)
    np.testing.assert_array_equal(
        res.to_numpy(), np.asarray(make_initial_grid(cfg))
    )
    assert res.steps_run == 0
    assert res.converged is None


@pytest.mark.parametrize("steps", [1, 7, 50])
def test_fixed_steps_match_oracle(steps):
    cfg = HeatConfig(nx=16, ny=12, steps=steps, backend="jnp")
    res = solve(cfg)
    want = oracle.run(oracle.init_grid(16, 12), steps)
    np.testing.assert_allclose(res.to_numpy(), want, rtol=1e-5, atol=1e-3)
    assert res.steps_run == steps


def test_converge_mode_reference_default_grid():
    # The reference's 20x20 default converges well before 10k steps.
    cfg = HeatConfig(nx=20, ny=20, steps=10_000, converge=True,
                     check_interval=20, eps=1e-3, backend="jnp")
    res = solve(cfg)
    assert res.converged is True
    assert res.steps_run % 20 == 0
    assert 0 < res.steps_run < 10_000
    assert res.residual < 1e-3


def test_converge_semantics_match_oracle():
    cfg = HeatConfig(nx=14, ny=14, steps=400, converge=True,
                     check_interval=10, eps=1e-2, backend="jnp")
    res = solve(cfg)
    want_u, want_k, want_conv, _ = oracle.run_converge(
        oracle.init_grid(14, 14), 400, 10, 1e-2
    )
    assert res.steps_run == want_k
    assert res.converged == want_conv
    np.testing.assert_allclose(res.to_numpy(), want_u, rtol=1e-5, atol=1e-2)


def test_converge_with_tiny_eps_runs_all_steps():
    # eps unreachable -> must run exactly `steps`, including the tail
    # chunk when steps is not a multiple of check_interval.
    cfg = HeatConfig(nx=12, ny=12, steps=47, converge=True,
                     check_interval=20, eps=1e-30, backend="jnp")
    res = solve(cfg)
    assert res.converged is False
    assert res.steps_run == 47
    fixed = solve(HeatConfig(nx=12, ny=12, steps=47, backend="jnp"))
    np.testing.assert_array_equal(res.to_numpy(), fixed.to_numpy())


def test_converge_steps_smaller_than_interval():
    cfg = HeatConfig(nx=12, ny=12, steps=5, converge=True,
                     check_interval=20, backend="jnp")
    res = solve(cfg)
    assert res.steps_run == 5
    assert res.converged is False


def test_3d_fixed_steps_match_oracle():
    cfg = HeatConfig(nx=8, ny=9, nz=10, steps=11, backend="jnp")
    res = solve(cfg)
    u = np.asarray(make_initial_grid(cfg), dtype=np.float64)
    for _ in range(11):
        u = oracle.step3d(u)
    np.testing.assert_allclose(res.to_numpy(), u, rtol=1e-5, atol=1e-3)


def test_3d_converge():
    cfg = HeatConfig(nx=10, ny=10, nz=10, steps=5000, converge=True,
                     check_interval=25, eps=1e-3, backend="jnp")
    res = solve(cfg)
    assert res.converged is True
    assert res.steps_run % 25 == 0


def test_diverged_converge_run_warns_at_runtime():
    # Runtime failure detection: a converge-mode run whose residual
    # goes non-finite (inf - inf = NaN) stops early with
    # converged=False AND emits a divergence warning, so the early
    # exit cannot be mistaken for quiet non-convergence.
    cfg = HeatConfig(nx=16, ny=16, steps=2000, cx=0.3, cy=0.3,
                     backend="jnp", converge=True, check_interval=20)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        res = solve(cfg)
    assert res.converged is False
    assert not np.isfinite(res.residual)
    assert any("diverged" in str(w.message) for w in caught
               if issubclass(w.category, RuntimeWarning))


def test_no_divergence_warning_when_no_check_ran():
    # The while-loop's inf residual seed is not a divergence: a stable
    # converge run with steps < check_interval never computes a
    # residual and must NOT warn (regression: the sentinel used to
    # trip the detector).
    cfg = HeatConfig(nx=16, ny=16, steps=10, converge=True,
                     check_interval=20, backend="jnp")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        res = solve(cfg)
    assert res.converged is False and res.steps_run == 10
    assert not any("diverged" in str(w.message) for w in caught)


def test_no_divergence_warning_on_stream_partial_chunk():
    # solve_stream's final partial chunk (steps not a multiple of
    # check_interval) also carries the sentinel; it must not warn.
    from parallel_heat_tpu.solver import solve_stream

    cfg = HeatConfig(nx=16, ny=16, steps=50, converge=True,
                     check_interval=20, backend="jnp")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        results = list(solve_stream(cfg, chunk_steps=20))
    assert results[-1].steps_run == 50
    assert not any("diverged" in str(w.message) for w in caught)


def test_float64_declines_pallas_and_runs():
    # Mosaic has no 64-bit types; every backend choice must route f64
    # to the XLA-fused path instead of crashing at trace time
    # (regression: backend="auto" raised NotImplementedError on TPU).
    import jax

    from parallel_heat_tpu.solver import _resolve_backend

    was = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        for backend in ("auto", "pallas", "jnp"):
            cfg = HeatConfig(nx=32, ny=32, steps=20, dtype="float64",
                             backend=backend)
            assert _resolve_backend(cfg) == "jnp"
            out = solve(cfg).to_numpy()
            assert out.dtype == np.float64
            assert np.isfinite(out).all()
    finally:
        jax.config.update("jax_enable_x64", was)
