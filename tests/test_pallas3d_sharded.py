"""Kernel H — the 3D shard-block Mosaic temporal kernel.

The sharded 3D path's Pallas kernel (`ops/pallas_stencil.py::
_build_temporal_block_3d` + `parallel/temporal.py::_pallas_round_3d`):
K-deep mixed halo exchange, K X-slab-streamed steps in VMEM, exact core
back. Runs in interpret mode here; `tools/hw_validate.py` drives the
same builder on real hardware. The jnp temporal rounds
(`block_multistep_3d`) are the oracle-adjacent path; the ultimate
oracle is the single-device jnp solve (bitwise equal to the jnp sharded
path by the invariant of SEMANTICS.md).
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.ops import pallas_stencil as ps
from parallel_heat_tpu.solver import _resolve_halo_depth, explain

F32_TOL = dict(rtol=1e-4, atol=1e-3)
BF16_TOL = dict(rtol=2e-2, atol=2.0)


def _oracle(**kw):
    return solve(HeatConfig(backend="jnp", **kw)).to_numpy().astype("f8")


@pytest.mark.parametrize("mesh,depth", [
    ((2, 2, 2), 4),   # all axes sharded
    ((2, 2, 1), 4),   # z unsharded (no z halo, no pad)
    ((1, 2, 2), 4),   # x unsharded (clamped slab windows)
    ((2, 1, 1), 2),   # only x sharded
])
def test_kernel_h_matches_jnp(mesh, depth):
    kw = dict(nx=16, ny=16, nz=16, steps=9)  # 9 % depth != 0: remainder
    cfg = HeatConfig(backend="pallas", mesh_shape=mesh, halo_depth=depth,
                     **kw)
    assert "kernel H" in explain(cfg)["path"]
    got = solve(cfg).to_numpy().astype("f8")
    np.testing.assert_allclose(got, _oracle(**kw), **F32_TOL)


def test_kernel_h_bf16():
    kw = dict(nx=16, ny=16, nz=16, steps=16, dtype="bfloat16")
    cfg = HeatConfig(backend="pallas", mesh_shape=(2, 2, 2), halo_depth=8,
                     **kw)
    assert "kernel H" in explain(cfg)["path"]
    got = solve(cfg).to_numpy().astype("f8")
    np.testing.assert_allclose(got, _oracle(**kw), **BF16_TOL)


def test_kernel_h_converge_matches_jnp():
    kw = dict(nx=16, ny=16, nz=16, steps=80, converge=True,
              check_interval=4, eps=1e-3)
    a = solve(HeatConfig(backend="jnp", **kw))
    b = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2, 2),
                         halo_depth=4, **kw))
    assert a.converged == b.converged
    assert abs(a.steps_run - b.steps_run) <= kw["check_interval"]
    np.testing.assert_allclose(a.to_numpy().astype("f8"),
                               b.to_numpy().astype("f8"), **F32_TOL)


def test_kernel_h_nonpow2_blocks():
    # 30x30x24 over (2,2,1): blocks (15,15,24) — divisor slab sweep
    # (sx in {15,5,3}), odd halo-extended planes in interpret mode.
    kw = dict(nx=30, ny=30, nz=24, steps=6)
    cfg = HeatConfig(backend="pallas", mesh_shape=(2, 2, 1), halo_depth=3,
                     **kw)
    assert "kernel H" in explain(cfg)["path"]
    got = solve(cfg).to_numpy().astype("f8")
    np.testing.assert_allclose(got, _oracle(**kw), **F32_TOL)


def test_kernel_h_diverging_boundary_exact():
    # Unstable coefficients blow the interior up to inf/NaN; Dirichlet
    # cells must stay bitwise exact (select-form pinning, no 0*inf).
    import warnings

    kw = dict(nx=16, ny=16, nz=16, steps=48, cx=0.9, cy=0.9, cz=0.9)
    ini = solve(HeatConfig(steps=0, **{k: v for k, v in kw.items()
                                       if k != "steps"})).to_numpy()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        out = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2, 2),
                               halo_depth=4, **kw)).to_numpy()
    assert not np.all(np.isfinite(out))
    for sl in [np.s_[0], np.s_[-1], np.s_[:, 0], np.s_[:, -1],
               np.s_[:, :, 0], np.s_[:, :, -1]]:
        np.testing.assert_array_equal(out[sl], ini[sl])


@pytest.mark.parametrize("mesh", [(2, 2, 2), (2, 2, 1), (1, 2, 2)])
def test_kernel_h_fused_matches_assembled_bitwise(mesh):
    # The default round (overlapped where x is sharded, monolithic
    # fused otherwise) must agree with the monolithic fused round —
    # bitwise on the inner planes, to f32 ulps on the k-deep x bands
    # (the band mini-problem's sweep shapes shift FMA contraction;
    # see _build_band_fix_3d's precision contract) — and the
    # monolithic fused round must agree with the assembled circular
    # layout bit-for-bit, mixed sharded/unsharded axes included.
    from parallel_heat_tpu import solver as slv

    # ONE K-round: after a second round the band ulps feed the inner
    # region (each round mixes boundary-adjacent values inward), so
    # the inner-bitwise property is per-round by construction.
    kw = dict(nx=32, ny=16, nz=16, steps=4)
    cfg = HeatConfig(backend="pallas", mesh_shape=mesh, halo_depth=4,
                     **kw)
    # Deferral additionally gates on multi-process (the band pass
    # costs ~11%/device and only a DCN hop repays it); single-process
    # runs must take the monolithic round.
    assert ps.pick_block_temporal_3d_deferred(
        cfg, ("x", "y", "z"), mesh) is None
    assert "deferred" not in explain(cfg)["path"]
    mp = pytest.MonkeyPatch()
    try:
        import jax as _jax

        mp.setattr(_jax, "process_count", lambda: 2)
        slv._build_runner.cache_clear()
        path = explain(cfg)["path"]
        assert "fused" in path
        dp = ps.pick_block_temporal_3d_deferred(cfg, ("x", "y", "z"),
                                                mesh)
        assert ("deferred x bands" in path) == (dp is not None)
        assert (dp is not None) == (mesh[0] > 1)
        default = solve(cfg).to_numpy()
        mp.setattr(ps, "_build_band_fix_3d", lambda *a, **k: None)
        slv._build_runner.cache_clear()
        assert "deferred" not in explain(cfg)["path"]
        fused = solve(cfg).to_numpy()
        mp.setattr(ps, "_build_temporal_block_3d_fused",
                   lambda *a, **k: None)
        slv._build_runner.cache_clear()
        assert "assembled" in explain(cfg)["path"]
        assembled = solve(cfg).to_numpy()
    finally:
        mp.undo()
        slv._build_runner.cache_clear()
    np.testing.assert_array_equal(fused, assembled)
    bx = 32 // mesh[0]
    K = 4
    # inner planes of every x-block: bitwise
    for b in range(mesh[0]):
        inner = np.s_[b * bx + K:(b + 1) * bx - K]
        np.testing.assert_array_equal(default[inner], fused[inner])
    np.testing.assert_allclose(default, fused, rtol=1e-6, atol=1e-3)


def test_overlap_3d_bulk_kernel_independent_of_x_ppermutes():
    # 3D analog of the 2D jaxpr proof: on a (2,2,1) mesh the round has
    # four ppermutes — two y shifts (phase 1) and two x shifts whose
    # payloads are built from the y-extended strips (phase 2). The
    # bulk pallas_call must not depend on the phase-2 ppermutes.
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from parallel_heat_tpu.parallel import temporal as tp
    from parallel_heat_tpu.parallel.mesh import make_heat_mesh
    from parallel_heat_tpu.utils.compat import shard_map as _shard_map
    from tests.test_temporal import _ancestor_eqns, _flat_jaxpr_levels

    import pytest as _pytest

    cfg = HeatConfig(nx=32, ny=16, nz=16, steps=8, backend="pallas",
                     mesh_shape=(2, 2, 1), halo_depth=4)
    mesh = make_heat_mesh((2, 2, 1))
    names = mesh.axis_names

    def local_round(u):
        bidx = tuple(lax.axis_index(n) for n in names)
        kw = dict(mesh_shape=(2, 2, 1), grid_shape=(32, 16, 16),
                  block_index=bidx, cx=0.1, cy=0.1, axis_names=names)
        fn = tp._pallas_round_3d(cfg, kw)
        assert fn is not None
        return fn(u, False)

    f = _shard_map(local_round, mesh=mesh, in_specs=P(*names),
                   out_specs=P(*names), check_vma=False)
    mp = _pytest.MonkeyPatch()
    try:
        mp.setattr(jax, "process_count", lambda: 2)
        jx = jax.make_jaxpr(f)(jnp.zeros((32, 16, 16), jnp.float32))
    finally:
        mp.undo()
    levels = [lv for lv in _flat_jaxpr_levels(jx.jaxpr)
              if any(e.primitive.name == "ppermute" for e in lv.eqns)]
    assert levels, "no ppermutes found in the traced round"
    body = levels[0]
    perms = [i for i, e in enumerate(body.eqns)
             if e.primitive.name == "ppermute"]
    assert len(perms) == 4
    phase2 = {i for i in perms
              if any(a in perms
                     for a in _ancestor_eqns(body, body.eqns[i]))}
    assert len(phase2) == 2
    pallas = [(i, e) for i, e in enumerate(body.eqns)
              if e.primitive.name == "pallas_call"]
    assert len(pallas) == 2
    bulk = min(pallas, key=lambda ie: len(ie[1].invars))
    band = max(pallas, key=lambda ie: len(ie[1].invars))
    assert len(band[1].invars) == len(bulk[1].invars) + 2
    assert not (phase2 & _ancestor_eqns(body, bulk[1])), \
        "bulk kernel depends on x-phase ppermutes: no overlap possible"
    assert phase2 & _ancestor_eqns(body, band[1]), \
        "band kernel should be the x-phase consumer"


def test_auto_depth_3d_resolves_to_kernel_h():
    # Bare sharded 3D pallas config: auto depth picks a K > 1 whose
    # round runs kernel H; the resolved depth is platform-independent
    # (the sweep applies hardware alignment rules even on CPU, so the
    # block needs a hardware-legal geometry: bz % 128 == 0).
    cfg = HeatConfig(nx=16, ny=16, nz=256, mesh_shape=(2, 2, 2),
                     backend="pallas")
    d = _resolve_halo_depth(cfg, "pallas")
    assert d > 1
    out = explain(cfg)
    assert "kernel H" in out["path"]
    assert out["halo_depth"] == f"{d} (auto)"
    # hardware-infeasible blocks (bz=8) resolve to 1 on every platform
    assert _resolve_halo_depth(
        HeatConfig(nx=16, ny=16, nz=16, mesh_shape=(2, 2, 2),
                   backend="pallas"), "pallas") == 1
    # and the full auto solve agrees with the oracle
    kw = dict(nx=16, ny=16, nz=256, steps=10)
    got = solve(HeatConfig(backend="pallas", mesh_shape=(2, 2, 2),
                           **kw)).to_numpy().astype("f8")
    np.testing.assert_allclose(got, _oracle(**kw), **F32_TOL)


def test_pick_block_temporal_3d_pins():
    # Flagship geometry: 512^3 over (2,2,2) -> (sx=32, K=4) under the
    # v5e parameter row (the CPU default) — the measured-best schedule
    # (62.3 Gcells*steps/s per device on v5e; the model's ranking was
    # validated against that sweep). A change here shifts the hardware
    # exchange schedule — re-measure before accepting.
    assert ps._pick_block_temporal_3d((256, 256, 256), (2, 2, 2),
                                      "float32") == (32, 4)
    # bf16 serves the model's raw pick (K=6). The rounds-3/4 "+1 depth
    # correction" was removed in round 5: the sweeps that motivated it
    # were host-enqueue-bound at these sub-ms rounds, and the
    # device-plane trace shows per-step time monotonically WORSE with
    # depth (50.3/52.3/52.6/55.7 us/step at K=5/6/7/8 —
    # tools/trace_small_h.py, REPORT 4d.1).
    assert ps._pick_block_temporal_3d((128, 128, 256), (2, 2, 2),
                                      "bfloat16") == (64, 6)
    # Non-pow2 (but tile-aligned) blocks pick divisor slabs.
    sx, k = ps._pick_block_temporal_3d((120, 120, 384), (2, 2, 1),
                                       "float32")
    assert 120 % sx == 0 and sx not in (4, 8, 16, 32, 64) and k >= 1
    # by not sublane-aligned declines (the out block's tile extent).
    assert ps._pick_block_temporal_3d((150, 150, 384), (2, 2, 1),
                                      "float32") is None
    # Hardware geometry guards: by % SUB and bz % LANE.
    assert ps._pick_block_xslab_3d((256, 256, 256), (4, 4, 4),
                                   "float32", 4, hw_align=True) is not None
    assert ps._pick_block_xslab_3d((256, 256, 160), (4, 4, 4),
                                   "float32", 4, hw_align=True) is None
    assert ps._pick_block_xslab_3d((256, 252, 256), (4, 4, 4),
                                   "float32", 4, hw_align=True) is None


def test_pick_depth_capped_at_smallest_block_extent():
    # Round-4 advisor high: the sub-f32 +1 correction must not step
    # past the smallest block extent (config.validate()'s multi-hop
    # bound). At (8,16,128) blocks the bf16 sweep's pick sits at
    # bmin=8; before the fix the correction auto-resolved depth 9 and
    # solve() silently returned NaNs.
    pick = ps._pick_block_temporal_3d((8, 16, 128), (2, 2, 1),
                                      "bfloat16")
    assert pick is not None and pick[1] <= 8
    # Scoring past the bound declines outright.
    assert ps._score_block_temporal_3d((8, 16, 128), (2, 2, 1),
                                       "bfloat16", 9) is None
    # End-to-end at the advisor's repro geometry: auto depth resolves
    # within bound and the sharded solve matches the jnp oracle (no
    # NaNs).
    kw = dict(nx=16, ny=32, nz=128, steps=10, dtype="bfloat16")
    cfg = HeatConfig(backend="pallas", mesh_shape=(2, 2, 1), **kw)
    depth = _resolve_halo_depth(cfg, "pallas")
    assert depth <= 8
    got = solve(cfg).to_numpy().astype("f8")
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, _oracle(**kw), **BF16_TOL)


def test_validate_allows_any_3d_pallas_depth():
    # 2D pallas requires depth == sublane count; 3D (kernel H) does not.
    HeatConfig(nx=16, ny=16, nz=16, mesh_shape=(2, 2, 2), halo_depth=3,
               backend="pallas").validate()
    with pytest.raises(ValueError, match="sublane|Mosaic"):
        HeatConfig(nx=32, ny=32, mesh_shape=(2, 2), halo_depth=3,
                   backend="pallas").validate()


def test_auto_depth_3d_small_bx_not_preempted_by_2d_guard():
    # Regression: the 2D sublane guard (blocks smaller than the sublane
    # count cannot host kernel G) must not pre-empt the 3D sweep —
    # kernel H has no sublane-depth constraint, so an (8,128,256) bf16
    # block still auto-deepens.
    cfg = HeatConfig(nx=16, ny=256, nz=256, mesh_shape=(2, 2, 1),
                     dtype="bfloat16", backend="pallas")
    assert _resolve_halo_depth(cfg, "pallas") > 1
