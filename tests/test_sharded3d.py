"""3D sharded equivalence on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve

MESHES_3D = [(2, 2, 2), (2, 4, 1), (1, 1, 8), (8, 1, 1)]


@pytest.mark.parametrize("mesh", MESHES_3D)
def test_3d_fixed_steps_sharded_equals_single(mesh):
    kw = dict(nx=8, ny=8, nz=8, steps=13, backend="jnp")
    want = solve(HeatConfig(**kw)).to_numpy()
    got = solve(HeatConfig(mesh_shape=mesh, **kw)).to_numpy()
    np.testing.assert_array_equal(got, want)


def test_3d_converge_sharded_equals_single():
    kw = dict(nx=8, ny=8, nz=8, steps=3000, converge=True,
              check_interval=20, eps=1e-3, backend="jnp")
    want = solve(HeatConfig(**kw))
    got = solve(HeatConfig(mesh_shape=(2, 2, 2), **kw))
    assert got.converged == want.converged is True
    assert got.steps_run == want.steps_run
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())
