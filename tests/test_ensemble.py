"""Ensemble engine tests (SEMANTICS.md "Ensemble").

The load-bearing pins, in contract order:

- **member parity**: every member of a batched run is bitwise the
  single-grid ``solve()`` of the same spec — fixed, converge and
  f32chunk modes, on the vmap path and the member-batched Pallas
  kernel M (interpret mode);
- **compaction invariance**: a member's trajectory does not depend on
  when (or whether) other members finish;
- **checkpoint/resume**: ensemble generations are crash-atomic, prune
  correctly, and a supervised interrupt + resume (and a guard-trip
  rollback) reproduce the uninterrupted run bit-exactly per member
  (the chaos cell);
- **packing**: the heatd scheduler coalesces compatible fresh jobs
  into one dispatch, fans per-member results back to the individual
  job records bitwise the solo runs, and demotes incompatible or
  interrupted packs to the proven solo path.
"""

import json
import os

import numpy as np
import pytest

from parallel_heat_tpu import EnsembleConfig, HeatConfig, solve
from parallel_heat_tpu.ensemble import checkpoint as ens_ckpt
from parallel_heat_tpu.ensemble.engine import (
    EnsembleSolver,
    ensemble_all_finite,
    ensemble_grid_stats,
    ensemble_path,
    packable,
)
from parallel_heat_tpu.ensemble.supervised import run_ensemble_supervised
from parallel_heat_tpu.supervisor import PermanentFailure, SupervisorPolicy
from parallel_heat_tpu.utils import checkpoint as ckpt


def _inits(n, shape, scale=5.0, seed=0):
    rng = np.random.RandomState(seed)
    return np.stack([rng.rand(*shape).astype(np.float32) * scale
                     for _ in range(n)])


def _bits(a):
    a = np.asarray(a)
    if a.dtype == np.float32:
        return a.view(np.uint32)
    if a.dtype.itemsize == 2:
        return a.view(np.uint16)
    return a.view(np.uint64)


def assert_member_bitwise(ens_grid, solo_grid, label=""):
    __tracebackhide__ = True
    assert np.array_equal(_bits(ens_grid), _bits(solo_grid)), label


# ---------------------------------------------------------------------------
# Member parity: batched == solo, bitwise
# ---------------------------------------------------------------------------

class TestParity:
    def test_fixed_jnp_bitwise(self):
        cfg = HeatConfig(nx=18, ny=22, steps=37, backend="jnp")
        inits = _inits(4, (18, 22))
        r = EnsembleSolver(cfg, 4).solve(initials=inits)
        assert r.converged is None and r.residual is None
        for i in range(4):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)
            assert int(r.steps_run[i]) == solo.steps_run == 37

    def test_converge_jnp_bitwise_per_member_verdicts(self):
        cfg = HeatConfig(nx=18, ny=22, steps=4000, converge=True,
                         eps=1e-3, check_interval=20, backend="jnp")
        base = _inits(1, (18, 22))[0]
        inits = np.stack([base * s for s in (0.1, 1.0, 10.0, 40.0)])
        r = EnsembleSolver(cfg, EnsembleConfig(
            members=4, window_rounds=2)).solve(initials=inits)
        # Different members converge at different steps...
        assert len(set(r.steps_run.tolist())) > 1
        for i in range(4):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)
            assert int(r.steps_run[i]) == solo.steps_run, i
            assert bool(r.converged[i]) == bool(solo.converged), i
            assert float(r.residual[i]) == float(solo.residual), i

    def test_converge_nonconverged_tail_bitwise(self):
        # A step budget that is NOT a multiple of check_interval and
        # too small to converge: the rem tail must run exactly like
        # solo's uninspected tail.
        cfg = HeatConfig(nx=16, ny=16, steps=53, converge=True,
                         eps=1e-12, check_interval=20, backend="jnp")
        inits = _inits(3, (16, 16))
        r = EnsembleSolver(cfg, 3).solve(initials=inits)
        for i in range(3):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)
            assert int(r.steps_run[i]) == solo.steps_run == 53
            assert not r.converged[i]

    def test_f32chunk_bitwise(self):
        import ml_dtypes

        cfg = HeatConfig(nx=16, ny=20, steps=48, dtype="bfloat16",
                         accumulate="f32chunk", backend="jnp")
        inits = _inits(3, (16, 20)).astype(ml_dtypes.bfloat16)
        r = EnsembleSolver(cfg, 3).solve(initials=inits)
        for i in range(3):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)

    def test_pallas_kernel_m_fixed_bitwise(self):
        cfg = HeatConfig(nx=16, ny=20, steps=23, backend="pallas")
        es = EnsembleSolver(cfg, 3)
        assert es.path == "M"
        inits = _inits(3, (16, 20))
        r = es.solve(initials=inits)
        for i in range(3):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)

    def test_pallas_kernel_m_converge_bitwise(self):
        cfg = HeatConfig(nx=16, ny=20, steps=3000, converge=True,
                         eps=1e-3, check_interval=20, backend="pallas")
        base = _inits(1, (16, 20))[0]
        inits = np.stack([base * s for s in (0.2, 1.0, 5.0)])
        r = EnsembleSolver(cfg, 3).solve(initials=inits)
        assert len(set(r.steps_run.tolist())) > 1
        for i in range(3):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)
            assert int(r.steps_run[i]) == solo.steps_run, i

    def test_3d_fixed_bitwise(self):
        cfg = HeatConfig(nx=10, ny=12, nz=8, steps=11, backend="jnp")
        rng = np.random.RandomState(3)
        inits = rng.rand(2, 10, 12, 8).astype(np.float32)
        r = EnsembleSolver(cfg, 2).solve(initials=inits)
        for i in range(2):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)

    def test_single_initial_broadcasts(self):
        cfg = HeatConfig(nx=16, ny=16, steps=9, backend="jnp")
        one = _inits(1, (16, 16))[0]
        r = EnsembleSolver(cfg, 3).solve(initials=one)
        solo = solve(cfg, initial=one)
        for i in range(3):
            assert_member_bitwise(r.grids[i], solo.grid, i)


# ---------------------------------------------------------------------------
# Compaction
# ---------------------------------------------------------------------------

class TestCompaction:
    def _cfg(self):
        return HeatConfig(nx=18, ny=22, steps=4000, converge=True,
                          eps=1e-3, check_interval=20, backend="jnp")

    def _spread_inits(self):
        base = _inits(1, (18, 22))[0]
        return np.stack([base * s for s in
                         (0.05, 0.1, 0.5, 1.0, 10.0, 40.0)])

    def test_compaction_triggers_and_is_invariant(self):
        cfg = self._cfg()
        inits = self._spread_inits()
        compacting = EnsembleSolver(cfg, EnsembleConfig(
            members=6, compact_threshold=0.75, window_rounds=1))
        r1 = compacting.solve(initials=inits)
        assert r1.compactions, "expected at least one compaction"
        never = EnsembleSolver(cfg, EnsembleConfig(
            members=6, compact_threshold=None, window_rounds=1))
        r2 = never.solve(initials=inits)
        assert not r2.compactions
        # A member's trajectory is invariant to when others finish.
        for i in range(6):
            assert_member_bitwise(r1.grids[i], r2.grids[i], i)
            assert int(r1.steps_run[i]) == int(r2.steps_run[i])
            assert float(r1.residual[i]) == float(r2.residual[i])

    def test_compaction_members_match_solo(self):
        cfg = self._cfg()
        inits = self._spread_inits()
        r = EnsembleSolver(cfg, EnsembleConfig(
            members=6, compact_threshold=0.75, window_rounds=1)
        ).solve(initials=inits)
        for i in range(6):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(r.grids[i], solo.grid, i)
            assert int(r.steps_run[i]) == solo.steps_run

    def test_window_rounds_orchestration_only(self):
        cfg = self._cfg()
        inits = self._spread_inits()
        a = EnsembleSolver(cfg, EnsembleConfig(
            members=6, window_rounds=1)).solve(initials=inits)
        b = EnsembleSolver(cfg, EnsembleConfig(
            members=6, window_rounds=7)).solve(initials=inits)
        for i in range(6):
            assert_member_bitwise(a.grids[i], b.grids[i], i)
            assert int(a.steps_run[i]) == int(b.steps_run[i])

    def test_compaction_halves_batch_at_default_threshold(self):
        cfg = self._cfg()
        inits = self._spread_inits()
        r = EnsembleSolver(cfg, EnsembleConfig(
            members=6, compact_threshold=0.5, window_rounds=1)
        ).solve(initials=inits)
        for _step, frm, to in r.compactions:
            assert to < frm / 2 + 1


# ---------------------------------------------------------------------------
# Config + explain surface
# ---------------------------------------------------------------------------

class TestConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="members"):
            EnsembleConfig(members=0).validate()
        with pytest.raises(ValueError, match="compact_threshold"):
            EnsembleConfig(compact_threshold=1.5).validate()
        with pytest.raises(ValueError, match="window_rounds"):
            EnsembleConfig(window_rounds=0).validate()
        EnsembleConfig(members=8, compact_threshold=None).validate()

    def test_orchestration_free_strips_only_orchestration(self):
        e = EnsembleConfig(members=8, compact_threshold=0.9,
                           window_rounds=11)
        s = e.orchestration_free()
        assert s.members == 8
        assert s.compact_threshold == EnsembleConfig().compact_threshold
        assert s.window_rounds == EnsembleConfig().window_rounds

    def test_json_round_trip(self):
        e = EnsembleConfig(members=5, compact_threshold=0.25)
        assert EnsembleConfig.from_json(e.to_json()) == e

    def test_sharded_config_refused(self):
        cfg = HeatConfig(nx=16, ny=16, mesh_shape=(2, 2))
        with pytest.raises(ValueError, match="single-device"):
            EnsembleSolver(cfg, 2)

    def test_explain_reports_path_and_packability(self):
        from parallel_heat_tpu.solver import explain

        doc = explain(HeatConfig(nx=16, ny=16, backend="jnp"),
                      ensemble=4)
        assert doc["ensemble"]["members"] == 4
        assert "vmap" in doc["ensemble"]["path"]
        assert doc["ensemble"]["packable"] is True
        doc = explain(HeatConfig(nx=16, ny=16, backend="pallas"),
                      ensemble=4)
        assert "kernel M" in doc["ensemble"]["path"]

    def test_kernel_m_vmem_budget_tighter_than_kernel_a(self):
        # Kernel M's per-instance footprint is ~3x kernel A's (no
        # in/out aliasing under a Mosaic grid + two scratch buffers):
        # a geometry near the solo VMEM limit must decline to vmap
        # rather than pick a kernel Mosaic would OOM (HL402's "picker
        # admits => Mosaic accepts" contract).
        from parallel_heat_tpu.ops.batched import (
            fits_vmem_batched, pick_ensemble_2d)
        from parallel_heat_tpu.ops.pallas_stencil import fits_vmem
        from parallel_heat_tpu.ops.tpu_params import params

        budget = params().resident_budget_bytes
        # A square f32 grid sized between the two bounds: fits kernel
        # A (2 buffers) but not kernel M (6 buffers).
        import math

        n = int(math.isqrt(budget // (4 * 4)))  # ~4 buffers' worth
        shape = (n, n)
        assert fits_vmem(shape, "float32")
        assert not fits_vmem_batched(shape, "float32")
        assert pick_ensemble_2d(shape, "float32") == "vmap"
        # Small grids admit on both tests.
        assert pick_ensemble_2d((64, 64), "float32") == "M"

    def test_packable_verdicts(self):
        ok, _ = packable(HeatConfig(nx=16, ny=16, backend="jnp"))
        assert ok
        ok, why = packable(HeatConfig(nx=64, ny=64,
                                      mesh_shape=(2, 2)))
        assert not ok and "solo" in why
        # Pallas where the solo pick is a streaming kernel: no
        # member-bitwise twin.
        big = HeatConfig(nx=4096, ny=4096, backend="pallas")
        path = ensemble_path(big)
        ok, _ = packable(big)
        assert (path == "M") == ok

    def test_batched_observers(self):
        cfg = HeatConfig(nx=16, ny=16, steps=5, backend="jnp")
        r = EnsembleSolver(cfg, 3).solve(initials=_inits(3, (16, 16)))
        fin = ensemble_all_finite(r.grids)
        assert fin.shape == (3,) and fin.all()
        stats = ensemble_grid_stats(r.grids)
        assert len(stats) == 3
        assert all(np.isfinite(s["heat"]) for s in stats)

    def test_guard_and_diag_ride_result(self):
        cfg = HeatConfig(nx=16, ny=16, steps=10, backend="jnp",
                         guard_interval=5, diag_interval=5)
        r = EnsembleSolver(cfg, 2).solve(initials=_inits(2, (16, 16)))
        assert r.finite is not None and r.finite.all()
        assert r.diagnostics is not None and len(r.diagnostics) == 2
        assert r.diagnostics[0]["step"] == 10

    def test_observation_fields_do_not_fork_batched_programs(self):
        # The member-axis edition of the HL101 contract: enabling
        # guard/diag on the ensemble must reuse the plain run's
        # compiled batched programs.
        from parallel_heat_tpu.ensemble import engine

        cfg = HeatConfig(nx=16, ny=16, steps=10, backend="jnp")
        inits = _inits(2, (16, 16))
        EnsembleSolver(cfg, 2).solve(initials=inits)
        before = engine._build_fixed_runner.cache_info()
        instrumented = cfg.replace(guard_interval=5, diag_interval=5)
        r = EnsembleSolver(instrumented, 2).solve(initials=inits)
        after = engine._build_fixed_runner.cache_info()
        assert after.misses == before.misses
        plain = EnsembleSolver(cfg, 2).solve(initials=inits)
        for i in range(2):
            assert_member_bitwise(r.grids[i], plain.grids[i], i)


# ---------------------------------------------------------------------------
# Ensemble checkpoints
# ---------------------------------------------------------------------------

class TestCheckpoint:
    def _state(self, b=3, shape=(8, 10), k=40):
        rng = np.random.RandomState(7)
        return {"k": k,
                "grids": rng.rand(b, *shape).astype(np.float32),
                "done": np.array([True, False, False][:b]),
                "res": np.array([1e-4, np.inf, 0.5][:b]),
                "steps": np.array([20, 40, 40][:b], np.int64)}

    def test_round_trip_bit_exact(self, tmp_path):
        stem = str(tmp_path / "ck" / "ens")
        cfg = HeatConfig(nx=8, ny=10, steps=100)
        ens = EnsembleConfig(members=3)
        st = self._state()
        path = ens_ckpt.save_ensemble_generation(stem, st, cfg, ens)
        assert ens_ckpt.latest_ensemble_checkpoint(stem) == path
        loaded, lcfg, lens, manifest = \
            ens_ckpt.load_ensemble_checkpoint(path, expect_config=cfg)
        assert np.array_equal(_bits(loaded["grids"]), _bits(st["grids"]))
        assert loaded["k"] == 40
        assert np.array_equal(loaded["done"], st["done"])
        assert np.array_equal(loaded["steps"], st["steps"])
        assert lens.members == 3
        assert [m["member"] for m in manifest] == [0, 1, 2]
        assert manifest[0]["converged"] is True
        assert manifest[1]["residual"] is None  # inf -> null in JSON

    def test_prune_keeps_newest(self, tmp_path):
        stem = str(tmp_path / "ens")
        cfg = HeatConfig(nx=8, ny=10, steps=100)
        ens = EnsembleConfig(members=3)
        for k in (10, 20, 30, 40):
            st = self._state(k=k)
            ens_ckpt.save_ensemble_generation(stem, st, cfg, ens, keep=2)
        paths = ens_ckpt.ensemble_generation_paths(stem)
        assert len(paths) == 2
        assert paths[-1].endswith(f".eg{40:012d}.npz")

    def test_torn_temp_invisible(self, tmp_path):
        stem = str(tmp_path / "ens")
        cfg = HeatConfig(nx=8, ny=10, steps=100)
        ens = EnsembleConfig(members=3)
        ens_ckpt.save_ensemble_generation(stem, self._state(k=10), cfg,
                                          ens)
        # A SIGKILLed writer's torn temp must never be discovered.
        torn = tmp_path / f".tmp-999-{os.path.basename(stem)}.eg" \
                          f"{20:012d}.npz"
        torn.write_bytes(b"torn")
        paths = ens_ckpt.ensemble_generation_paths(stem)
        assert len(paths) == 1 and paths[0].endswith(".eg" +
                                                     f"{10:012d}.npz")

    def test_config_mismatch_refused(self, tmp_path):
        stem = str(tmp_path / "ens")
        cfg = HeatConfig(nx=8, ny=10, steps=100)
        path = ens_ckpt.save_ensemble_generation(
            stem, self._state(), cfg, EnsembleConfig(members=3))
        with pytest.raises(ValueError, match="nx"):
            ens_ckpt.load_ensemble_checkpoint(
                path, expect_config=cfg.replace(nx=16, ny=10))


# ---------------------------------------------------------------------------
# Supervised ensemble: the chaos cells
# ---------------------------------------------------------------------------

def _policy(every=50, **kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("sleep_fn", lambda s: None)
    return SupervisorPolicy(checkpoint_every=every, **kw)


class TestSupervised:
    def test_complete_matches_plain_solve(self, tmp_path):
        cfg = HeatConfig(nx=16, ny=20, steps=200, backend="jnp")
        inits = _inits(3, (16, 20))
        sres = run_ensemble_supervised(cfg, 3, tmp_path / "ck",
                                       policy=_policy(),
                                       initials=inits)
        assert not sres.interrupted and sres.steps_done == 200
        for i in range(3):
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(sres.result.grids[i], solo.grid, i)
        assert sres.checkpoints_written >= 4  # gen0 + cadence + final

    @pytest.mark.chaos
    def test_interrupt_resume_bit_exact_per_member(self, tmp_path):
        cfg = HeatConfig(nx=16, ny=20, steps=200, backend="jnp",
                         guard_interval=50)
        inits = _inits(3, (16, 20))
        full = run_ensemble_supervised(cfg, 3, tmp_path / "full" / "ck",
                                       policy=_policy(),
                                       initials=inits)
        calls = [0]

        def interrupt():
            calls[0] += 1
            return "deadline" if calls[0] == 2 else None

        s1 = run_ensemble_supervised(cfg, 3, tmp_path / "res" / "ck",
                                     policy=_policy(), initials=inits,
                                     interrupt=interrupt)
        assert s1.interrupted and s1.signal_name == "deadline"
        assert 0 < s1.steps_done < 200
        s2 = run_ensemble_supervised(cfg, 3, tmp_path / "res" / "ck",
                                     policy=_policy())
        assert not s2.interrupted and s2.steps_done == 200
        for i in range(3):
            assert_member_bitwise(s2.result.grids[i],
                                  full.result.grids[i], i)

    @pytest.mark.chaos
    def test_converge_interrupt_resume_bit_exact(self, tmp_path):
        cfg = HeatConfig(nx=18, ny=22, steps=4000, converge=True,
                         eps=1e-3, check_interval=20, backend="jnp")
        base = _inits(1, (18, 22))[0]
        inits = np.stack([base * s for s in (0.1, 1.0, 40.0)])
        full = run_ensemble_supervised(cfg, 3, tmp_path / "full" / "ck",
                                       policy=_policy(every=100),
                                       initials=inits)
        calls = [0]

        def interrupt():
            calls[0] += 1
            return "SIGTERM" if calls[0] == 3 else None

        s1 = run_ensemble_supervised(cfg, 3, tmp_path / "res" / "ck",
                                     policy=_policy(every=100),
                                     initials=inits,
                                     interrupt=interrupt)
        assert s1.interrupted
        s2 = run_ensemble_supervised(cfg, 3, tmp_path / "res" / "ck",
                                     policy=_policy(every=100))
        assert not s2.interrupted
        for i in range(3):
            assert_member_bitwise(s2.result.grids[i],
                                  full.result.grids[i], i)
            assert int(s2.result.steps_run[i]) == \
                int(full.result.steps_run[i])
            assert bool(s2.result.converged[i]) == \
                bool(full.result.converged[i])

    @pytest.mark.chaos
    def test_guard_trip_rollback_recovers_bitwise(self, tmp_path,
                                                  monkeypatch):
        cfg = HeatConfig(nx=16, ny=20, steps=200, backend="jnp",
                         guard_interval=50)
        inits = _inits(3, (16, 20))
        clean = run_ensemble_supervised(cfg, 3,
                                        tmp_path / "clean" / "ck",
                                        policy=_policy(),
                                        initials=inits)
        # One transient false guard verdict: the supervisor must roll
        # back to the newest generation, replay, and land bitwise.
        from parallel_heat_tpu.ensemble import supervised as sup

        real = sup.ensemble_all_finite
        fired = [False]

        def flaky(grids):
            out = real(grids)
            if not fired[0]:
                fired[0] = True
                return np.zeros_like(out)
            return out

        monkeypatch.setattr(sup, "ensemble_all_finite", flaky)
        sres = run_ensemble_supervised(cfg, 3, tmp_path / "r" / "ck",
                                       policy=_policy(max_retries=2),
                                       initials=inits)
        assert sres.guard_trips == 1 and sres.rollbacks == 1
        for i in range(3):
            assert_member_bitwise(sres.result.grids[i],
                                  clean.result.grids[i], i)

    def test_unstable_config_fails_fast(self, tmp_path):
        cfg = HeatConfig(nx=16, ny=16, steps=400, cx=0.4, cy=0.4,
                         backend="jnp", guard_interval=50)
        with pytest.warns(RuntimeWarning):
            with pytest.raises(PermanentFailure) as ei:
                run_ensemble_supervised(cfg, 2, tmp_path / "ck",
                                        policy=_policy())
        assert ei.value.kind == "unstable"

    def test_stem_lock_held(self, tmp_path):
        cfg = HeatConfig(nx=16, ny=16, steps=100, backend="jnp")
        stem = ckpt.checkpoint_stem(str(tmp_path / "ck"))
        release = ckpt.acquire_stem_lock(stem)
        try:
            with pytest.raises(ckpt.StemLockError):
                run_ensemble_supervised(cfg, 2, stem, policy=_policy())
        finally:
            release()

    def test_member_stems_flush_solo_resumable(self, tmp_path):
        cfg = HeatConfig(nx=16, ny=20, steps=100, backend="jnp")
        inits = _inits(2, (16, 20))
        stems = [str(tmp_path / f"m{i}" / "ck") for i in range(2)]
        run_ensemble_supervised(cfg, 2, tmp_path / "ens" / "ck",
                                policy=_policy(), initials=inits,
                                member_stems=stems)
        for i, stem in enumerate(stems):
            src = ckpt.latest_checkpoint(stem)
            assert src is not None
            grid, step, _ = ckpt.load_checkpoint(src, cfg)
            assert step == 100
            solo = solve(cfg, initial=inits[i])
            assert_member_bitwise(grid, solo.grid, i)


# ---------------------------------------------------------------------------
# heatd packing
# ---------------------------------------------------------------------------

class _DoneHandle:
    def __init__(self, rc):
        self.rc = rc
        self.pid = os.getpid()

    def poll(self):
        return self.rc

    def terminate(self):
        pass

    def kill(self):
        pass


@pytest.fixture
def packing_daemon(tmp_path):
    from parallel_heat_tpu.service import worker
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig

    q = str(tmp_path / "q")
    record = {"packs": [], "solos": []}

    def launcher(job_id=None, worker_id=None, attempt=1,
                 deadline_t=None, job_ids=None):
        if job_ids is not None:
            record["packs"].append(list(job_ids))
            rc = worker.execute_pack(q, job_ids, worker_id)
        else:
            record["solos"].append(job_id)
            rc = worker.execute_job(q, job_id, worker_id, attempt,
                                    deadline_t=deadline_t)
        return _DoneHandle(rc)

    t = [0.0]
    # cache_results=False: these tests pin the PACKING mechanics
    # (grouping, splitting, dwell) — with the result cache on, later
    # twins of a completed pack member serve in O(1) instead of
    # packing, which is the better outcome but not the one under test
    # (the cache/packing interplay is covered in tests/test_cache.py).
    cfg = HeatdConfig(root=q, slots=1, pack_jobs=True, pack_max=8,
                      cache_results=False,
                      launcher=launcher, clock=lambda: t[0],
                      sleep_fn=lambda s: None)
    daemon = Heatd(cfg)
    yield daemon, t, record
    daemon.store.close()


def _spool(daemon, job_id, config, **kw):
    from parallel_heat_tpu.service.store import JobSpec

    daemon.store.spool_submit(JobSpec(job_id=job_id,
                                      config=dict(config), **kw))


_PACK_CONFIG = {"nx": 16, "ny": 16, "steps": 60, "backend": "jnp"}


class TestPacking:
    def _drive(self, daemon, t, n=6):
        for _ in range(n):
            t[0] += 1.0
            daemon.step(t[0])

    def test_compatible_jobs_pack_and_fan_out_bitwise(
            self, packing_daemon):
        daemon, t, record = packing_daemon
        jids = [f"job-{i}" for i in range(3)]
        for j in jids:
            _spool(daemon, j, _PACK_CONFIG, checkpoint_every=20)
        self._drive(daemon, t)
        jobs, anomalies = daemon.store.replay()
        assert not anomalies
        assert all(jobs[j].state == "completed" for j in jids)
        assert record["packs"] == [jids] and not record["solos"]
        # One worker id across the pack; per-member records committed.
        assert len({jobs[j].worker for j in jids}) == 1
        solo = solve(HeatConfig(**_PACK_CONFIG))
        for j in jids:
            rec = daemon.store.read_result(j, 1)
            assert rec["outcome"] == "completed"
            assert rec["pack"] == "job-0" and rec["pack_size"] == 3
            assert rec["steps_done"] == 60
            src = ckpt.latest_checkpoint(daemon.store.checkpoint_stem(j))
            grid, step, _ = ckpt.load_checkpoint(src)
            assert step == 60
            assert_member_bitwise(grid, solo.grid, j)

    def test_incompatible_specs_do_not_pack(self, packing_daemon):
        daemon, t, record = packing_daemon
        _spool(daemon, "a", _PACK_CONFIG)
        _spool(daemon, "b", dict(_PACK_CONFIG, nx=20))
        self._drive(daemon, t, n=8)
        jobs, anomalies = daemon.store.replay()
        assert not anomalies
        assert jobs["a"].state == jobs["b"].state == "completed"
        assert not record["packs"]
        assert sorted(record["solos"]) == ["a", "b"]

    def test_faulted_and_deadline_jobs_run_solo(self, packing_daemon):
        daemon, t, record = packing_daemon
        _spool(daemon, "a", _PACK_CONFIG,
               faults={"transient_on_chunks": [1]})
        _spool(daemon, "b", _PACK_CONFIG, deadline_s=9999.0)
        _spool(daemon, "c", _PACK_CONFIG)
        self._drive(daemon, t, n=10)
        jobs, anomalies = daemon.store.replay()
        assert not anomalies
        assert all(v.state == "completed" for v in jobs.values())
        assert not record["packs"]  # no two compatible fresh jobs

    def test_pack_max_splits_batches(self, packing_daemon):
        daemon, t, record = packing_daemon
        daemon.config.pack_max = 2
        jids = [f"j{i}" for i in range(5)]
        for j in jids:
            _spool(daemon, j, _PACK_CONFIG)
        self._drive(daemon, t, n=12)
        jobs, anomalies = daemon.store.replay()
        assert not anomalies
        assert all(jobs[j].state == "completed" for j in jids)
        assert all(len(p) == 2 for p in record["packs"])
        assert len(record["packs"]) == 2 and len(record["solos"]) == 1

    def test_pack_wait_holds_lone_job_then_releases(self, tmp_path):
        from parallel_heat_tpu.service import worker
        from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
        from parallel_heat_tpu.service.store import JobSpec

        q = str(tmp_path / "qw")
        t = [1000.0]

        def launcher(job_id=None, worker_id=None, attempt=1,
                     deadline_t=None, job_ids=None):
            if job_ids is not None:
                return _DoneHandle(
                    worker.execute_pack(q, job_ids, worker_id))
            return _DoneHandle(
                worker.execute_job(q, job_id, worker_id, attempt))

        daemon = Heatd(HeatdConfig(
            root=q, slots=2, pack_jobs=True, pack_wait_s=5.0,
            launcher=launcher, clock=lambda: t[0],
            sleep_fn=lambda s: None))
        daemon.store.spool_submit(JobSpec(job_id="solo-hold",
                                          config=dict(_PACK_CONFIG)))
        daemon.step(t[0])
        # Journal stamps accepted_t with the real wall clock; fetch it
        # and probe the dwell window relative to that stamp.
        jobs, _ = daemon.store.replay()
        acc = jobs["solo-hold"].accepted_t
        t[0] = acc + 1.0
        daemon.step(t[0])
        jobs, _ = daemon.store.replay()
        assert jobs["solo-hold"].state == "queued"  # held by the dwell
        t[0] = acc + 6.0
        daemon.step(t[0])
        t[0] += 1.0
        daemon.step(t[0])
        jobs, _ = daemon.store.replay()
        assert jobs["solo-hold"].state == "completed"
        daemon.store.close()

    def test_unpackable_path_demotes_to_solo(self, packing_daemon,
                                             monkeypatch):
        # The worker's runtime packability re-check: force a refusal
        # and prove the members demote to solo requeues, then finish.
        daemon, t, record = packing_daemon
        from parallel_heat_tpu.ensemble import engine

        monkeypatch.setattr(engine, "packable",
                            lambda cfg: (False, "forced for test"))
        for j in ("x", "y"):
            _spool(daemon, j, _PACK_CONFIG)
        self._drive(daemon, t, n=10)
        jobs, anomalies = daemon.store.replay()
        assert not anomalies
        assert jobs["x"].state == jobs["y"].state == "completed"
        assert record["packs"] == [["x", "y"]]
        assert sorted(record["solos"]) == ["x", "y"]
        # The demoted attempt journaled a requeue, not a failure.
        assert jobs["x"].requeues == 1 and not jobs["x"].failures


# ---------------------------------------------------------------------------
# Telemetry + report tooling
# ---------------------------------------------------------------------------

class TestTelemetryReport:
    def test_ensemble_events_and_report_section(self, tmp_path):
        import importlib.util

        from parallel_heat_tpu.utils.telemetry import Telemetry

        cfg = HeatConfig(nx=18, ny=22, steps=4000, converge=True,
                         eps=1e-3, check_interval=20, backend="jnp")
        base = _inits(1, (18, 22))[0]
        inits = np.stack([base * s for s in (0.1, 1.0, 10.0, 40.0)])
        path = tmp_path / "m.jsonl"
        with Telemetry(str(path)) as tel:
            EnsembleSolver(cfg, EnsembleConfig(
                members=4, compact_threshold=0.75, window_rounds=1)
            ).solve(initials=inits, telemetry=tel)
        events = [json.loads(line) for line in
                  path.read_text().splitlines()]
        kinds = {e["event"] for e in events}
        assert {"run_header", "ensemble_window", "member_converged",
                "member_end", "ensemble_compaction"} <= kinds
        header = next(e for e in events if e["event"] == "run_header")
        assert header["ensemble"]["members"] == 4
        ends = [e for e in events if e["event"] == "member_end"]
        assert sorted(e["member"] for e in ends) == [0, 1, 2, 3]

        spec = importlib.util.spec_from_file_location(
            "metrics_report", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "metrics_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        doc = mod.summarize(events)
        ens = doc["ensemble"]
        assert ens["members"] == 4 and ens["converged_members"] == 4
        assert ens["compactions"]
        assert ens["live_trajectory"][0]["batch"] == 4
        assert ens["converge_steps"]["min"] < \
            ens["converge_steps"]["max"]
        assert sum(b["count"] for b in
                   ens["converge_steps"]["histogram"]) == 4
        # The text renderer must include the section without crashing.
        assert "ensemble:" in mod.render_text(doc)

    def test_fleet_packing_counters(self, packing_daemon):
        import importlib.util

        daemon, t, record = packing_daemon
        for j in ("p0", "p1", "p2"):
            _spool(daemon, j, _PACK_CONFIG)
        for _ in range(6):
            t[0] += 1.0
            daemon.step(t[0])
        spec = importlib.util.spec_from_file_location(
            "metrics_report", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "metrics_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        doc = mod.summarize_fleet(daemon.store.root)
        f = doc["fleet"]
        assert f["completed"] == 3
        assert f["packed_jobs"] == 3
        assert f["pack_dispatches"] == 1
        assert f["jobs_per_dispatch"] == 3.0
        assert "packing" in mod.render_fleet_text(doc)


def test_inline_pack_stream_falls_back_to_spec_trace(packing_daemon):
    # Review regression (ISSUE 12): an inline-launched pack crosses no
    # env boundary, so the pack's shared telemetry stream must inherit
    # the LEADER's committed spec trace (execute_job's fallback,
    # applied to packs) — otherwise heattrace cannot join the stream
    # to its submits.
    import glob
    import json as _json

    from parallel_heat_tpu.utils.tracing import (
        dispatch_span_id,
        worker_span_id,
    )

    daemon, t, record = packing_daemon
    jids = ["tp-0", "tp-1"]
    for i, j in enumerate(jids):
        _spool(daemon, j, _PACK_CONFIG, checkpoint_every=20,
               trace={"trace_id": f"trace-{i}",
                      "span_id": f"s-submit-{j}"})
    for _ in range(6):
        t[0] += 1.0
        daemon.step(t[0])
    jobs, anomalies = daemon.store.replay()
    assert not anomalies
    assert all(jobs[j].state == "completed" for j in jids)
    assert record["packs"] == [jids]
    # the reducer carried each member's own trace off its journal line
    assert [jobs[j].trace_id for j in jids] == ["trace-0", "trace-1"]
    (stream,) = glob.glob(os.path.join(
        daemon.store.root, "telemetry", "pack-*.jsonl"))
    with open(stream) as f:
        ev = [_json.loads(ln) for ln in f if ln.strip()]
    # the shared stream traces under the LEADER's spec trace, as a
    # worker child of the leader's dispatch span
    assert all(e["trace_id"] == "trace-0" for e in ev)
    assert all(e["span_id"] == worker_span_id("tp-0", 1) for e in ev)
    assert all(e["parent_span_id"] == dispatch_span_id("tp-0", 1)
               for e in ev)
    assert all(e["job_id"] == "tp-0" for e in ev)
