"""heatlint: the static contract-verification suite (SEMANTICS.md
"Statically verified contracts").

Every rule gets at least one seeded-violation (true-positive) fixture
and one clean (true-negative) fixture; the cache-key audit additionally
gets the regression the suite exists for — a new ``HeatConfig`` field
that is NOT stripped from ``_build_runner`` cache keys must fail. The
CLI round-trips (exit codes, --json, baseline suppression) run the real
``tools/heatlint.py`` as a subprocess, and the acceptance gate — the
repo's own tree is clean at ``--fail-on error`` — runs last.
"""

import dataclasses
import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from parallel_heat_tpu.analysis import ALL_RULES, LAYERS, layer_of
from parallel_heat_tpu.analysis.astlint import lint_file, lint_paths
from parallel_heat_tpu.analysis.contracts import (
    _audit_runner_callers, audit_cache_keys, audit_dirichlet,
    audit_donation, audit_f32chunk)
from parallel_heat_tpu.analysis.findings import (
    Baseline, Finding, apply_baseline, gates, load_baseline)
from parallel_heat_tpu.analysis.kernels import (
    KernelTarget, _source_kernel_names, audit_kernels)
from parallel_heat_tpu.analysis.spmd import (
    AUDIT_MESHES_2D, SpmdTarget, audit_spmd)
from parallel_heat_tpu.utils.compat import shard_map

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_HEATLINT = os.path.join(_ROOT, "tools", "heatlint.py")


def _fixture(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


def _rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# HL101 cache-key partition
# ---------------------------------------------------------------------------

def _toy_config(extra_fields=()):
    """A doctored config dataclass: a(semantic), b(observation-only),
    plus any ``(name, default)`` extras — with ``replace`` like the
    real HeatConfig."""
    fields = [("a", int, dataclasses.field(default=1)),
              ("b", int, dataclasses.field(default=0))]
    fields += [(n, type(d), dataclasses.field(default=d))
               for n, d in extra_fields]
    cls = dataclasses.make_dataclass(
        "ToyConfig", fields, frozen=True,
        namespace={"replace": lambda self, **kw:
                   dataclasses.replace(self, **kw)})
    return cls


def _toy_strip(cfg):
    return cfg.replace(b=0) if cfg.b != 0 else cfg


def test_hl101_clean_partition():
    cls = _toy_config()
    out = audit_cache_keys(config_cls=cls, semantic=("a",),
                           observation=("b",), strip=_toy_strip,
                           scan_paths=[])
    assert out == []


def test_hl101_unclassified_field_fails():
    cls = _toy_config(extra_fields=(("new_knob", 3),))
    out = audit_cache_keys(config_cls=cls, semantic=("a",),
                           observation=("b",), strip=_toy_strip,
                           scan_paths=[])
    assert any("new_knob" in f.message and f.severity == "error"
               for f in out)


def test_hl101_observation_field_not_stripped_fails():
    cls = _toy_config(extra_fields=(("verbose", 0),))
    # 'verbose' is declared observation-only but the strip site leaves
    # it in place — the exact silent-cache-fork bug the rule exists for.
    out = audit_cache_keys(config_cls=cls, semantic=("a",),
                           observation=("b", "verbose"),
                           strip=_toy_strip, scan_paths=[])
    assert any("'verbose' is NOT stripped" in f.message for f in out)


def test_hl101_semantic_field_erased_fails():
    cls = _toy_config()

    def over_strip(cfg):  # erases the SEMANTIC field too
        return cfg.replace(a=1, b=0)

    out = audit_cache_keys(config_cls=cls, semantic=("a",),
                           observation=("b",), strip=over_strip,
                           scan_paths=[])
    assert any("semantic field 'a' is erased" in f.message for f in out)


def test_hl101_stale_partition_entry_fails():
    cls = _toy_config()
    out = audit_cache_keys(config_cls=cls, semantic=("a", "ghost"),
                           observation=("b",), strip=_toy_strip,
                           scan_paths=[])
    assert any("'ghost' does not exist" in f.message for f in out)


def test_hl101_new_heatconfig_field_regression():
    """THE acceptance regression: a new field added to the real
    HeatConfig without classification (and therefore without stripping)
    must fail the audit — against the real partition and the real
    solver strip site."""
    from parallel_heat_tpu.config import (OBSERVATION_ONLY_FIELDS,
                                          SEMANTIC_FIELDS, HeatConfig)
    from parallel_heat_tpu.solver import _observer_free

    doctored = dataclasses.make_dataclass(
        "DoctoredConfig",
        [("trace_level", int, dataclasses.field(default=0))],
        bases=(HeatConfig,), frozen=True)
    out = audit_cache_keys(config_cls=doctored,
                           semantic=SEMANTIC_FIELDS,
                           observation=OBSERVATION_ONLY_FIELDS,
                           strip=_observer_free, scan_paths=[])
    assert any("'trace_level'" in f.message and f.severity == "error"
               for f in out), out
    # ...and classifying it observation-only IS stripping it (the strip
    # site reads the declaration), so the audit then passes.
    import parallel_heat_tpu.config as _cfg
    out2 = audit_cache_keys(
        config_cls=doctored, semantic=SEMANTIC_FIELDS,
        observation=OBSERVATION_ONLY_FIELDS + ("trace_level",),
        strip=_observer_free, scan_paths=[])
    # _observer_free reads the module-level tuple, so patch it for the
    # positive half.
    orig = _cfg.OBSERVATION_ONLY_FIELDS
    try:
        _cfg.OBSERVATION_ONLY_FIELDS = orig + ("trace_level",)
        out3 = audit_cache_keys(
            config_cls=doctored, semantic=SEMANTIC_FIELDS,
            observation=_cfg.OBSERVATION_ONLY_FIELDS,
            strip=_observer_free, scan_paths=[])
        assert out3 == []
    finally:
        _cfg.OBSERVATION_ONLY_FIELDS = orig
    # without the patch the strip site ignores the new name -> caught
    assert any("'trace_level' is NOT stripped" in f.message
               for f in out2)


def test_hl101_real_partition_is_clean():
    assert audit_cache_keys(scan_paths=[]) == []


def test_hl101_ensemble_partition_is_clean():
    """The second HL101 audit (PR 9): the EnsembleConfig semantic /
    orchestration partition against its own strip site, run by the
    registered rule alongside the HeatConfig audit."""
    from parallel_heat_tpu.analysis.contracts import audit_cache_keys_all
    from parallel_heat_tpu.config import (
        ENSEMBLE_ORCHESTRATION_FIELDS,
        ENSEMBLE_SEMANTIC_FIELDS,
        EnsembleConfig,
    )

    out = audit_cache_keys(
        config_cls=EnsembleConfig,
        semantic=ENSEMBLE_SEMANTIC_FIELDS,
        observation=ENSEMBLE_ORCHESTRATION_FIELDS,
        strip=lambda c: c.orchestration_free(), scan_paths=[])
    assert out == []
    # The registered rule runs BOTH partitions and stays clean.
    assert [f for f in audit_cache_keys_all()
            if f.severity == "error"] == []


def test_hl101_new_ensemble_field_regression():
    """A new EnsembleConfig field added without classification must
    fail the registered audit — the member-axis edition of the
    new-HeatConfig-field regression."""
    from parallel_heat_tpu.config import (
        ENSEMBLE_ORCHESTRATION_FIELDS,
        ENSEMBLE_SEMANTIC_FIELDS,
        EnsembleConfig,
    )

    doctored = dataclasses.make_dataclass(
        "DoctoredEnsemble",
        [("pack_hint", int, dataclasses.field(default=0))],
        bases=(EnsembleConfig,), frozen=True)
    out = audit_cache_keys(
        config_cls=doctored,
        semantic=ENSEMBLE_SEMANTIC_FIELDS,
        observation=ENSEMBLE_ORCHESTRATION_FIELDS,
        strip=lambda c: c.orchestration_free(), scan_paths=[])
    assert any("'pack_hint'" in f.message and f.severity == "error"
               for f in out), out


def test_hl101_unstripped_build_runner_caller(tmp_path):
    bad = _fixture(tmp_path, "bad_caller.py", """
        from parallel_heat_tpu.solver import _build_runner

        def bench(cfg):
            runner, spec = _build_runner(cfg)
            return runner
    """)
    out = _audit_runner_callers([bad])
    assert [(f.rule, f.symbol) for f in out] == [("HL101", "bench")]

    good = _fixture(tmp_path, "good_caller.py", """
        from parallel_heat_tpu.solver import _build_runner, _observer_free

        def bench(cfg):
            cfg = _observer_free(cfg)
            runner, spec = _build_runner(cfg)
            return runner

        def bench_inline(cfg):
            return _build_runner(_observer_free(cfg))
    """)
    assert _audit_runner_callers([good]) == []


def test_hl101_method_and_module_scope_callers(tmp_path):
    # Class methods and module-level script lines are call sites too.
    bad = _fixture(tmp_path, "scoped_callers.py", """
        from parallel_heat_tpu.solver import _build_runner

        class Bench:
            def run(self, cfg):
                runner, _ = _build_runner(cfg)
                return runner

        runner, _ = _build_runner(make_config())
    """)
    out = _audit_runner_callers([bad])
    assert {(f.rule, f.symbol) for f in out} == {
        ("HL101", "run"), ("HL101", "<module>")}


def test_hl101_outer_scope_strip_covers_nested_closure(tmp_path):
    good = _fixture(tmp_path, "nested_strip.py", """
        from parallel_heat_tpu.solver import _build_runner, _observer_free

        def stream(cfg):
            cfg = _observer_free(cfg)

            def _build():
                return _build_runner(cfg)

            return _build()
    """)
    assert _audit_runner_callers([good]) == []


# ---------------------------------------------------------------------------
# HL102 donation safety
# ---------------------------------------------------------------------------

def test_hl102_read_after_donate(tmp_path):
    bad = _fixture(tmp_path, "bad_donate.py", """
        def stream(runner, cfg, u):
            step = _compiled_for(runner, cfg, u)
            out = step(u)
            checksum = u.sum()      # read after the dispatch donated u
            return out, checksum
    """)
    out = audit_donation(path=bad)
    assert any(f.rule == "HL102" and "'u' is read after" in f.message
               for f in out)


def test_hl102_rebind_before_read_is_clean(tmp_path):
    good = _fixture(tmp_path, "good_donate.py", """
        def stream(runner, cfg, u):
            step = _compiled_for(runner, cfg, u)
            u = step(u)             # rebound from the dispatch result
            checksum = u.sum()
            return u, checksum
    """)
    assert audit_donation(path=good) == []


def test_hl102_raw_output_escape(tmp_path):
    bad = _fixture(tmp_path, "bad_escape.py", """
        def stream(runner, cfg, u, pending):
            step = _compiled_for(runner, cfg, u)

            def _dispatch():  # heatlint: dispatch-region
                nonlocal u
                out = step(u)
                pending.append(out)   # raw donated buffer escapes
                u = out

            _dispatch()
    """)
    out = audit_donation(path=bad)
    assert any(f.rule == "HL102" and "escapes" in f.message
               for f in out)


def test_hl102_copy_protected_escape_is_clean(tmp_path):
    good = _fixture(tmp_path, "good_escape.py", """
        import jax.numpy as jnp

        def stream(runner, cfg, u, pending):
            step = _compiled_for(runner, cfg, u)

            def _dispatch():  # heatlint: dispatch-region
                nonlocal u
                out = step(u)
                keep = jnp.copy(out)  # donation-protected copy
                pending.append(keep)
                u = out

            _dispatch()
    """)
    assert audit_donation(path=good) == []


def test_hl102_multiline_donating_call_is_clean(tmp_path):
    # The donated argument's own continuation line is part of the
    # dispatch, not a read-after-donate (a formatter rewrap must not
    # turn `make lint` red).
    good = _fixture(tmp_path, "wrapped_donate.py", """
        def stream(runner, cfg, u):
            step = _compiled_for(runner, cfg, u)
            u = step(
                u)
            return u
    """)
    assert audit_donation(path=good) == []


def test_hl102_real_solver_is_clean():
    assert audit_donation() == []


# ---------------------------------------------------------------------------
# HL103 Dirichlet write-set
# ---------------------------------------------------------------------------

def _target(fn, n=16):
    sds = jax.ShapeDtypeStruct((n, n), jnp.float32)
    return ("fixture", fn, sds, (n, n))


def test_hl103_boundary_write_caught():
    def bad(u):  # writes row 0 — the Dirichlet boundary
        return u.at[0:4, 1:5].set(jnp.zeros((4, 4), u.dtype))

    out = audit_dirichlet(targets=[_target(bad)])
    assert any(f.rule == "HL103" and "touches the Dirichlet boundary"
               in f.message for f in out)


def test_hl103_interior_write_clean():
    def good(u):
        return u.at[1:-1, 1:-1].set(u[1:-1, 1:-1] * 0.5)

    assert audit_dirichlet(targets=[_target(good)]) == []


def test_hl103_upper_edge_write_caught():
    def bad(u):  # start interior, but extent reaches the last row
        return u.at[2:16, 1:15].set(jnp.zeros((14, 14), u.dtype))

    out = audit_dirichlet(targets=[_target(bad)])
    assert any("touches the Dirichlet boundary" in f.message
               for f in out)


def test_hl103_dynamic_index_unprovable():
    def dyn(u):
        i = (u[0, 0] > 0).astype(jnp.int32) + 1
        return jax.lax.dynamic_update_slice(
            u, jnp.zeros((2, 2), u.dtype), (i, i))

    out = audit_dirichlet(targets=[_target(dyn)])
    assert any(f.rule == "HL103" and "non-literal" in f.message
               for f in out)


def test_hl103_real_solver_programs_clean():
    assert audit_dirichlet() == []


def test_hl103_implicit_update_program_pinned():
    # The implicit-stepping satellite (SEMANTICS.md "Implicit
    # stepping"): the default target matrix TRACES the implicit
    # update programs — the whole V-cycle, per-step while_loop and
    # storage round-off included — so their grid-shaped writes are
    # proven interior-only, not just the explicit loops'. Pin the
    # labels so a refactor cannot silently drop the coverage.
    from parallel_heat_tpu.analysis.contracts import (
        _default_dirichlet_targets)

    labels = {t[0] for t in _default_dirichlet_targets()}
    assert {"jnp-2d-implicit-be", "jnp-2d-implicit-cn"} <= labels


def test_hl2xx_scan_scope_covers_multigrid_module():
    # HL2xx AST coverage pinned over the new implicit modules: the
    # default scan path set must reach ops/multigrid.py (and keep
    # reaching the solver), so the AST hygiene rules — dispatch-region
    # sync bans, kernel-name literals, lock discipline — audit the
    # V-cycle code like everything else.
    from parallel_heat_tpu.analysis.astlint import (
        _iter_py_files, default_scan_paths)

    files = {os.path.basename(p) for p in
             _iter_py_files(default_scan_paths())}
    assert "multigrid.py" in files and "solver.py" in files


def test_hl2xx_scan_scope_covers_tune_package():
    # Same pin for the measured-autotuning package: the default scan
    # path set must reach every tune/ module, so the AST hygiene
    # rules — wallclock-in-traced bans, lock discipline, unused
    # imports — audit the search/DB/consult layers like everything
    # else (the autotuner times code; timing code is exactly where
    # HL201/HL202 violations breed).
    from parallel_heat_tpu.analysis.astlint import (
        _iter_py_files, default_scan_paths)

    files = {os.path.relpath(p).replace(os.sep, "/") for p in
             _iter_py_files(default_scan_paths())}
    assert {"parallel_heat_tpu/tune/__init__.py",
            "parallel_heat_tpu/tune/db.py",
            "parallel_heat_tpu/tune/search.py"} <= files
    assert "tools/autotune.py" in files


def test_hl2xx_scan_scope_covers_obs_package():
    # Same pin for the flight-recorder package: the default scan path
    # set must reach every obs/ module, so the AST hygiene rules audit
    # the recorder/exposition/alert layers like everything else (the
    # recorder runs inside the serving perimeter; a stray blocking
    # call or wallclock-in-traced slip there stalls the fleet, not a
    # report).
    from parallel_heat_tpu.analysis.astlint import (
        _iter_py_files, default_scan_paths)

    files = {os.path.relpath(p).replace(os.sep, "/") for p in
             _iter_py_files(default_scan_paths())}
    assert {"parallel_heat_tpu/obs/__init__.py",
            "parallel_heat_tpu/obs/series.py",
            "parallel_heat_tpu/obs/expo.py",
            "parallel_heat_tpu/obs/alerts.py"} <= files


# ---------------------------------------------------------------------------
# HL104 f32chunk accumulation chain
# ---------------------------------------------------------------------------

def _chain_target(fn, n=16):
    return ("fixture", fn, jax.ShapeDtypeStruct((n, n), jnp.bfloat16))


def test_hl104_midchain_downcast_caught():
    def bad(u):
        x = u.astype(jnp.float32) * 2.0
        y = x.astype(jnp.bfloat16)          # mid-chain rounding point
        return (y * jnp.bfloat16(2.0)).astype(jnp.bfloat16)

    out = audit_f32chunk(targets=[_chain_target(bad)])
    assert any(f.rule == "HL104" and "mid-chain downcast" in f.message
               for f in out)


def test_hl104_single_boundary_downcast_clean():
    def good(u):
        x = u.astype(jnp.float32)
        x = x * 2.0 + 1.0
        return x.astype(jnp.bfloat16)       # the one rounding event

    assert audit_f32chunk(targets=[_chain_target(good)]) == []


def test_hl104_real_f32chunk_chain_clean():
    assert audit_f32chunk() == []


# ---------------------------------------------------------------------------
# HL201 blocking-in-dispatch
# ---------------------------------------------------------------------------

def test_hl201_blocking_call_in_region(tmp_path):
    bad = _fixture(tmp_path, "bad_block.py", """
        import jax

        def loop(step, u):
            def _dispatch():  # heatlint: dispatch-region
                v = step(u)
                jax.block_until_ready(v)     # serializes the pipeline
                r = float(v[0, 0])           # host scalar read
                return v, r
            return _dispatch()
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL201"]
    assert len(out) == 2
    assert all(f.symbol == "loop._dispatch" for f in out)


def test_hl201_block_markers(tmp_path):
    bad = _fixture(tmp_path, "bad_markers.py", """
        import time

        def run(step, u):
            u = step(u)
            # heatlint: begin dispatch-region
            time.sleep(0.1)
            # heatlint: end dispatch-region
            time.sleep(0.2)   # outside: fine
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL201"]
    assert [f.line for f in out] == [7]


def test_hl201_unterminated_begin_marker_reported(tmp_path):
    # Deleting the end marker must not silently disable the rule: the
    # dangling begin is itself a finding, and begin..EOF still scans.
    bad = _fixture(tmp_path, "dangling.py", """
        import jax

        def run(step, u):
            # heatlint: begin dispatch-region
            u = step(u)
            jax.block_until_ready(u)
            return u
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL201"]
    assert any("unterminated" in f.message for f in out)
    assert any("block_until_ready" in f.message for f in out)


def test_hl201_outside_region_clean(tmp_path):
    good = _fixture(tmp_path, "good_block.py", """
        import jax

        def loop(step, u):
            v = step(u)
            jax.block_until_ready(v)   # no dispatch region here
            return float(v[0, 0])
    """)
    assert [f for f in lint_file(good) if f.rule == "HL201"] == []


def test_hl201_nonblocking_in_region_clean(tmp_path):
    good = _fixture(tmp_path, "good_async.py", """
        def loop(step, u, pending):
            def _dispatch():  # heatlint: dispatch-region
                v = step(u)
                v.copy_to_host_async()
                pending.append(v)
                return v
            return _dispatch()
    """)
    assert [f for f in lint_file(good) if f.rule == "HL201"] == []


# ---------------------------------------------------------------------------
# HL202 wallclock-in-traced
# ---------------------------------------------------------------------------

def test_hl202_clock_in_jit(tmp_path):
    bad = _fixture(tmp_path, "bad_clock.py", """
        import time
        import jax

        @jax.jit
        def step(u):
            t0 = time.perf_counter()   # baked in at trace time
            return u * 2.0
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL202"]
    assert len(out) == 1 and "time.perf_counter" in out[0].message


def test_hl202_rng_in_loop_body(tmp_path):
    bad = _fixture(tmp_path, "bad_rng.py", """
        import random
        from jax import lax

        def run(u, n):
            def body(i, u):
                return u * random.random()   # one sample, reused forever
            return lax.fori_loop(0, n, body, u)
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL202"]
    assert len(out) == 1 and out[0].symbol == "run.body"


def test_hl202_host_side_clock_clean(tmp_path):
    good = _fixture(tmp_path, "good_clock.py", """
        import time
        import jax

        @jax.jit
        def step(u):
            return u * 2.0

        def run(u):
            t0 = time.perf_counter()   # host side: fine
            u = step(u)
            return u, time.perf_counter() - t0
    """)
    assert [f for f in lint_file(good) if f.rule == "HL202"] == []


def test_hl202_jax_random_clean(tmp_path):
    good = _fixture(tmp_path, "good_jaxrandom.py", """
        import jax

        @jax.jit
        def step(u, key):
            return u + jax.random.normal(key, u.shape)   # traced RNG
    """)
    assert [f for f in lint_file(good) if f.rule == "HL202"] == []


# ---------------------------------------------------------------------------
# HL203 pallas-name
# ---------------------------------------------------------------------------

def test_hl203_missing_and_bad_names(tmp_path):
    bad = _fixture(tmp_path, "bad_names.py", """
        import jax
        from jax.experimental import pallas as pl

        def build_anon(kernel, shape):
            return pl.pallas_call(
                kernel, out_shape=jax.ShapeDtypeStruct(shape, "float32"))

        def build_misnamed(kernel, shape):
            return pl.pallas_call(
                kernel, name="stencil_2d",
                out_shape=jax.ShapeDtypeStruct(shape, "float32"))
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL203"]
    assert {f.symbol for f in out} == {"build_anon", "build_misnamed"}


def test_hl203_heat_name_clean(tmp_path):
    good = _fixture(tmp_path, "good_names.py", """
        import jax
        from jax.experimental import pallas as pl

        def build(kernel, shape):
            return pl.pallas_call(
                kernel, name="heat_tile_2d",
                out_shape=jax.ShapeDtypeStruct(shape, "float32"))
    """)
    assert [f for f in lint_file(good) if f.rule == "HL203"] == []


# ---------------------------------------------------------------------------
# HL204 lock-discipline
# ---------------------------------------------------------------------------

def test_hl204_unlocked_mutation(tmp_path):
    bad = _fixture(tmp_path, "bad_lock.py", """
        import threading

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.events = []
                self.dead = False

            def emit(self, rec):
                with self._lock:
                    self.events.append(rec)
                    self.dead = False

            def kill(self):
                self.dead = True          # races emit()'s critical section
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL204"]
    assert len(out) == 1
    assert out[0].symbol == "Sink.kill" and "self.dead" in out[0].message


def test_hl204_locked_everywhere_clean(tmp_path):
    good = _fixture(tmp_path, "good_lock.py", """
        import threading

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.events = []
                self.dead = False         # __init__: not yet shared

            def emit(self, rec):
                with self._lock:
                    self.events.append(rec)

            def kill(self):
                with self._lock:
                    self.dead = True

            def snapshot(self):
                return list(self.events)  # read-only: not a mutation
    """)
    assert [f for f in lint_file(good) if f.rule == "HL204"] == []


def test_hl204_lockless_class_ignored(tmp_path):
    good = _fixture(tmp_path, "no_lock.py", """
        class Stats:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1     # single-threaded by design: no lock attr
    """)
    assert [f for f in lint_file(good) if f.rule == "HL204"] == []


# ---------------------------------------------------------------------------
# HL205 unused-import
# ---------------------------------------------------------------------------

def test_hl205_unused_import(tmp_path):
    bad = _fixture(tmp_path, "bad_imports.py", """
        import os
        import json

        def dump(x):
            return json.dumps(x)
    """)
    out = [f for f in lint_file(bad) if f.rule == "HL205"]
    assert len(out) == 1 and "'os'" in out[0].message


def test_hl205_noqa_and_init_skipped(tmp_path):
    waived = _fixture(tmp_path, "waived.py", """
        import os  # noqa: F401 — re-exported for callers
    """)
    assert [f for f in lint_file(waived) if f.rule == "HL205"] == []
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("import os\n")
    assert lint_file(str(pkg / "__init__.py")) == []


def test_lint_paths_walks_directories(tmp_path):
    _fixture(tmp_path, "a.py", "import os\n")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b.py").write_text("import sys\n")
    out = lint_paths([str(tmp_path)], rules={"HL205"})
    assert {os.path.basename(f.file) for f in out} == {"a.py", "b.py"}


# ---------------------------------------------------------------------------
# HL301/HL302/HL303 SPMD layer — shared fixture plumbing
# ---------------------------------------------------------------------------
#
# Each fixture is a tiny shard_map program over a 1D 4-device mesh with
# a seeded protocol violation; check_vma=False mirrors the compat shim
# on pre-vma jax (nothing checks replication dynamically — exactly the
# gap HL303 closes statically).

_DOWN = [(0, 1), (1, 2), (2, 3)]
_UP = [(1, 0), (2, 1), (3, 2)]


def _mesh1d(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("x",))


def _sm(body, out_specs=P("x")):
    def fn(u):
        return shard_map(body, _mesh1d(), (P("x"),), out_specs,
                         check_vma=False)(u)
    return fn


def _stgt(fn, label="fixture", family="fam", variant="v"):
    return SpmdTarget(label, family, variant, fn,
                      jax.ShapeDtypeStruct((16, 16), jnp.float32))


def _spmd_msgs(targets):
    return [(f.rule, f.message) for f in audit_spmd(targets=targets)]


# ---------------------------------------------------------------------------
# HL301 halo permutation protocol
# ---------------------------------------------------------------------------

def test_hl301_incomplete_shift_caught():
    bad = _sm(lambda b: b + lax.ppermute(b, "x", [(0, 1), (1, 2)])
              + lax.ppermute(b, "x", _UP))
    msgs = _spmd_msgs([_stgt(bad)])
    assert any(r == "HL301" and "INCOMPLETE" in m for r, m in msgs)


def test_hl301_non_bijection_caught():
    bad = _sm(lambda b: b + lax.ppermute(b, "x", [(0, 1), (0, 2)]))
    msgs = _spmd_msgs([_stgt(bad)])
    assert any(r == "HL301" and "not a partial bijection" in m
               for r, m in msgs)


def test_hl301_non_neighbor_hop_caught():
    bad = _sm(lambda b: b + lax.ppermute(b, "x", [(0, 2), (2, 0)]))
    msgs = _spmd_msgs([_stgt(bad)])
    assert any(r == "HL301" and "not a one-hop neighbor shift" in m
               for r, m in msgs)


def test_hl301_unpaired_direction_caught():
    # A complete down-shift with no symmetric up-shift: the MPI
    # deadlock-freedom pairing argument fails.
    bad = _sm(lambda b: b + lax.ppermute(b, "x", _DOWN))
    msgs = _spmd_msgs([_stgt(bad)])
    assert any(r == "HL301" and "unpaired shift direction" in m
               for r, m in msgs)


def test_hl301_symmetric_exchange_clean():
    good = _sm(lambda b: b + lax.ppermute(b, "x", _DOWN)
               + lax.ppermute(b, "x", _UP))
    assert _spmd_msgs([_stgt(good)]) == []


def test_audit_meshes_cover_test_sharded():
    """The static proof must cover every topology the dynamic parity
    suite (tests/test_sharded.py) exercises."""
    from tests.test_sharded import MESHES

    assert set(MESHES) <= set(AUDIT_MESHES_2D)


def test_hl3xx_real_solver_programs_clean():
    """The acceptance gate for the SPMD layer: the real solver's
    sharded programs across the whole audit mesh matrix carry a
    provably-correct exchange protocol (and the audit is non-vacuous —
    a matrix that traces zero shard_maps reports itself)."""
    assert audit_spmd() == []


def test_audit_targets_cover_overlap_schedules():
    """The default target matrix pins every halo_overlap schedule
    into the temporal families (SEMANTICS.md "Overlapped exchange"):
    HL301 audits the overlapped/pipelined programs' ppermute tables
    and HL302's cross-variant rule proves the schedules of one
    geometry exchange IDENTICAL tables — a schedule that permuted
    differently would fail lint before it could deadlock a mixed
    deployment."""
    from parallel_heat_tpu.analysis.spmd import default_spmd_targets

    targets, _skips = default_spmd_targets()
    fams = {}
    for t in targets:
        fams.setdefault(t.family, set()).add(t.variant)
    # jnp 2D temporal: the auto (overlap) variants + the phase pin.
    assert {"fixed", "converge", "fixed-phase"} <= \
        fams["jnp-2d-temporal"]
    # kernel G: auto resolves to the pipelined round, and the
    # deferred + phase spellings ride the same family.
    assert {"fixed", "fixed-overlap", "fixed-phase"} <= \
        fams["pallas-2d-temporal"]
    # 3D deferred-x rounds vs phase-separated.
    assert {"fixed", "fixed-phase"} <= fams["jnp-3d-temporal"]


# ---------------------------------------------------------------------------
# HL302 collective divergence
# ---------------------------------------------------------------------------

def test_hl302_varying_cond_predicate_caught():
    def body(b):
        pred = lax.axis_index("x") == 0  # varies across the mesh
        return lax.cond(pred,
                        lambda x: lax.ppermute(x, "x", _DOWN)
                        + lax.ppermute(x, "x", _UP),
                        lambda x: x, b)

    msgs = _spmd_msgs([_stgt(_sm(body))])
    assert any(r == "HL302" and "DIFFERENT collective sequences" in m
               for r, m in msgs)


def test_hl302_replicated_cond_predicate_clean():
    # The converge-tail pattern: the predicate comes out of a pmax, so
    # every device takes the same branch — differing branch collectives
    # are legal.
    def body(b):
        pred = lax.pmax(jnp.max(b), "x") > 0
        return lax.cond(pred,
                        lambda x: lax.ppermute(x, "x", _DOWN)
                        + lax.ppermute(x, "x", _UP),
                        lambda x: x, b)

    assert _spmd_msgs([_stgt(_sm(body))]) == []


def test_hl302_varying_while_predicate_caught():
    def body(b):
        def cond_fn(c):
            i, _x = c
            return i < lax.axis_index("x") + 1  # device-varying bound

        def body_fn(c):
            i, x = c
            return i + 1, (lax.ppermute(x, "x", _DOWN)
                           + lax.ppermute(x, "x", _UP))

        _i, x = lax.while_loop(cond_fn, body_fn, (0, b))
        return x

    msgs = _spmd_msgs([_stgt(_sm(body))])
    assert any(r == "HL302" and "while_loop body performs" in m
               for r, m in msgs)


def test_hl302_cross_variant_exchange_mismatch_caught():
    # fixed exchanges halos, converge doesn't: a mixed deployment of
    # the two compiled programs would hang.
    good = _sm(lambda b: b + lax.ppermute(b, "x", _DOWN)
               + lax.ppermute(b, "x", _UP))
    other = _sm(lambda b: b * 2.0)
    msgs = _spmd_msgs([
        _stgt(good, "famX/fixed", family="famX", variant="fixed"),
        _stgt(other, "famX/converge", family="famX", variant="converge"),
    ])
    assert any(r == "HL302" and "different halo tables" in m
               for r, m in msgs)


def test_hl302_identical_variants_clean():
    mk = lambda: _sm(lambda b: b + lax.ppermute(b, "x", _DOWN)
                     + lax.ppermute(b, "x", _UP))
    msgs = _spmd_msgs([
        _stgt(mk(), "famY/fixed", family="famY", variant="fixed"),
        _stgt(mk(), "famY/converge", family="famY", variant="converge"),
    ])
    assert msgs == []


# ---------------------------------------------------------------------------
# HL303 replication proof
# ---------------------------------------------------------------------------

def test_hl303_unreplicated_scalar_output_caught():
    def body(b):
        return b, jnp.float32(lax.axis_index("x"))  # varying scalar

    msgs = _spmd_msgs([_stgt(_sm(body, out_specs=(P("x"), P())))])
    assert any(r == "HL303" and "provably varies over" in m
               for r, m in msgs)


def test_hl303_pmax_reduced_scalar_clean():
    # The convergence-residual pattern: reduced over every mesh axis
    # before it feeds host control flow.
    def body(b):
        return b, lax.pmax(jnp.max(b), "x")

    assert _spmd_msgs([_stgt(_sm(body, out_specs=(P("x"), P())))]) == []


def test_hl303_ppermute_output_varies():
    # ppermute GROWS the varying set: a received halo declared
    # replicated is a lie even though the value "came from" one device.
    def body(b):
        h = lax.ppermute(jnp.max(b), "x", _DOWN)
        return b, h

    msgs = _spmd_msgs([_stgt(_sm(body, out_specs=(P("x"), P())))])
    assert any(r == "HL303" for r, m in msgs)


def test_hl303_while_carry_chain_needs_fixpoint():
    """Variance flows through a CHAIN of loop carries (a <- axis_index,
    b <- a, c <- b needs one propagation pass per link): the dataflow
    must iterate to a fixpoint — any iteration cap under-approximates
    and would 'prove' the chain's tail replicated."""
    def body(u):
        def cond_fn(c):
            return c[0] < 3  # replicated bound: no HL302 noise

        def body_fn(c):
            i, a, b, _cc = c
            return (i + 1, jnp.float32(lax.axis_index("x")), a, b)

        _i, _a, _b, cc = lax.while_loop(
            cond_fn, body_fn,
            (0, jnp.float32(0), jnp.float32(0), jnp.float32(0)))
        return u, cc

    msgs = _spmd_msgs([_stgt(_sm(body, out_specs=(P("x"), P())))])
    assert any(r == "HL303" and "provably varies over" in m
               for r, m in msgs)


# ---------------------------------------------------------------------------
# HL401-HL404 Pallas kernel safety — shared fixture plumbing
# ---------------------------------------------------------------------------

from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

_N = 128


def _sds(shape, dt="float32"):
    return jax.ShapeDtypeStruct(shape, dt)


def _strip_call(kernel, n_strips=2, rows=16, scratch_rows=8):
    """A minimal kernel-B-shaped pallas_call: ANY-space input DMA'd
    into double-buffered VMEM scratch, one output strip per grid step.
    The fixture kernels seed their violations inside ``kernel``."""
    return pl.pallas_call(
        kernel,
        out_shape=_sds((rows, _N)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(n_strips,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((rows // n_strips, _N),
                                   lambda s: (s, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, scratch_rows, _N), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        name="heat_probe_fixture",
    )


def _kernel_msgs(call, args, **kw):
    t = KernelTarget("fixture", call, args)
    return [(f.rule, f.message) for f in audit_kernels(targets=[t], **kw)]


def test_kernel_eval_bitwise_ints_exact():
    """lax's and/or/xor/not are BITWISE: boolean shortcutting over ints
    (2 & 1 == 0 vs truthy-and -> 1) would resolve a DMA offset to the
    wrong value and bounds-check the wrong window. Ints evaluate
    bitwise, bools boolean, mixed/float goes UNKNOWN."""
    from parallel_heat_tpu.analysis.kernels import UNKNOWN, _KernelEval

    ev = _KernelEval((1,), (0,), lambda *a: None, [])
    unk = [UNKNOWN]
    assert ev._scalar_prim("and", None, [2, 1], unk) == [0]
    assert ev._scalar_prim("or", None, [2, 1], unk) == [3]
    assert ev._scalar_prim("xor", None, [3, 1], unk) == [2]
    assert ev._scalar_prim("not", None, [0], unk) == [~0]
    assert ev._scalar_prim("and", None, [True, False], unk) == [False]
    assert ev._scalar_prim("not", None, [False], unk) == [True]
    assert ev._scalar_prim("and", None, [2.0, 1], unk) is unk


# ---------------------------------------------------------------------------
# HL401 DMA in-bounds
# ---------------------------------------------------------------------------

def test_hl401_clean_schedule_passes():
    def k(u_hbm, out_ref, scratch, sems):
        s = pl.program_id(0)
        cp = pltpu.make_async_copy(u_hbm.at[pl.ds(s * 8, 8), :],
                                   scratch.at[s % 2], sems.at[s % 2])
        cp.start()
        cp.wait()
        out_ref[:] = scratch[s % 2] * 2.0

    assert _kernel_msgs(_strip_call(k), [_sds((16, _N))]) == []


def test_hl401_out_of_bounds_window_caught():
    def k(u_hbm, out_ref, scratch, sems):
        s = pl.program_id(0)
        # 16-row windows over a 16-row ref: instance 1 reads [16, 32).
        cp = pltpu.make_async_copy(u_hbm.at[pl.ds(s * 16, 16), :],
                                   scratch.at[s % 2, pl.ds(0, 16), :],
                                   sems.at[s % 2])
        cp.start()
        cp.wait()
        out_ref[:] = scratch[s % 2, 0:8, :] * 2.0

    msgs = _kernel_msgs(_strip_call(k, scratch_rows=16), [_sds((16, _N))])
    assert any(r == "HL401" and "out of bounds" in m for r, m in msgs)


def test_hl401_data_dependent_window_unprovable():
    def k(u_hbm, off_ref, out_ref, scratch, sems):
        s = pl.program_id(0)
        off = off_ref[0]  # runtime SMEM value: not statically derivable
        cp = pltpu.make_async_copy(u_hbm.at[pl.ds(off, 8), :],
                                   scratch.at[s % 2], sems.at[s % 2])
        cp.start()
        cp.wait()
        out_ref[:] = scratch[s % 2] * 2.0

    call = pl.pallas_call(
        k,
        out_shape=_sds((16, _N)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(2,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((8, _N), lambda s: (s, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((2, 8, _N), jnp.float32),
                            pltpu.SemaphoreType.DMA((2,))],
        ),
        name="heat_probe_fixture",
    )
    msgs = [(f.rule, f.message) for f in audit_kernels(
        targets=[KernelTarget("fixture", call,
                              [_sds((16, _N)), _sds((1,), "int32")])])]
    assert any(r == "HL401" and "not statically derivable" in m
               for r, m in msgs)


def test_hl4xx_real_kernels_clean_and_all_sites_covered():
    """The acceptance gate for the kernel layer: every builder passes
    at its representative geometry, and the audit's coverage
    cross-check pins all 20 pallas_call sites across
    pallas_stencil.py, the member-batched ops/batched.py (kernel M,
    PR 9) and the multigrid transfer kernels in ops/multigrid.py
    (heat_mg_restrict/heat_mg_prolong, the implicit-stepping PR) — a
    21st site fails this count AND the uncovered-site cross-check
    until it gets an audit target."""
    assert audit_kernels() == []
    names = _source_kernel_names()
    assert len(names) == 20
    assert "heat_m_ens_vmem_multistep" in names
    assert "heat_mg_restrict" in names and "heat_mg_prolong" in names


def test_hl401_uncovered_site_mechanism():
    # The 18th-kernel guard: auditing with an injected target list and
    # coverage enforcement must flag every real site as uncovered.
    def k(u_ref, out_ref):
        out_ref[:] = u_ref[:] * 2.0

    call = pl.pallas_call(k, out_shape=_sds((8, _N)),
                          in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                          out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                          name="heat_probe_fixture")
    out = audit_kernels(targets=[KernelTarget("fixture", call,
                                              [_sds((8, _N))])],
                        check_coverage=True)
    uncovered = {f.symbol for f in out
                 if "not covered by any kernel-audit target" in f.message}
    assert uncovered == set(_source_kernel_names())


# ---------------------------------------------------------------------------
# HL402 VMEM budget
# ---------------------------------------------------------------------------

def _plain_call():
    def k(u_ref, out_ref):
        out_ref[:] = u_ref[:] * 2.0

    return pl.pallas_call(
        k, out_shape=_sds((8, _N)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        name="heat_probe_fixture")


def test_hl402_over_budget_caught():
    msgs = _kernel_msgs(_plain_call(), [_sds((8, _N))], limit_bytes=1024)
    assert any(r == "HL402" and "exceeds" in m for r, m in msgs)


def test_hl402_within_budget_clean():
    assert _kernel_msgs(_plain_call(), [_sds((8, _N))]) == []


# ---------------------------------------------------------------------------
# HL403 semaphore discipline
# ---------------------------------------------------------------------------

def test_hl403_wait_without_start_caught():
    def k(u_hbm, out_ref, scratch, sems):
        s = pl.program_id(0)
        pltpu.make_async_copy(u_hbm.at[pl.ds(s * 8, 8), :],
                              scratch.at[s % 2], sems.at[s % 2]).wait()
        out_ref[:] = scratch[s % 2] * 2.0

    msgs = _kernel_msgs(_strip_call(k), [_sds((16, _N))])
    assert any(r == "HL403" and "NO outstanding copy" in m
               for r, m in msgs)


def test_hl403_leaked_start_caught():
    def k(u_hbm, out_ref, scratch, sems):
        s = pl.program_id(0)
        pltpu.make_async_copy(u_hbm.at[pl.ds(s * 8, 8), :],
                              scratch.at[s % 2], sems.at[s % 2]).start()
        out_ref[:] = jnp.zeros_like(out_ref)

    msgs = _kernel_msgs(_strip_call(k), [_sds((16, _N))])
    assert any(r == "HL403" and "never waited" in m for r, m in msgs)


def test_hl403_slot_reuse_in_flight_caught():
    def k(u_hbm, out_ref, scratch, sems):
        a = pltpu.make_async_copy(u_hbm.at[pl.ds(0, 8), :],
                                  scratch.at[0], sems.at[0])
        b = pltpu.make_async_copy(u_hbm.at[pl.ds(8, 8), :],
                                  scratch.at[0], sems.at[1])
        a.start()
        b.start()  # same destination slot while a is still in flight
        a.wait()
        b.wait()
        out_ref[:] = scratch[0] * 2.0

    msgs = _kernel_msgs(_strip_call(k), [_sds((16, _N))])
    assert any(r == "HL403" and "double-buffer slot reused" in m
               for r, m in msgs)


# ---------------------------------------------------------------------------
# HL404 grid/BlockSpec coverage
# ---------------------------------------------------------------------------

def _zeros_kernel(u_ref, out_ref):
    out_ref[:] = jnp.zeros_like(out_ref)


def test_hl404_ragged_block_caught():
    call = pl.pallas_call(
        _zeros_kernel, out_shape=_sds((8, _N)), grid=(2,),
        in_specs=[pl.BlockSpec((3, _N), lambda s: (s, 0))],
        out_specs=pl.BlockSpec((4, _N), lambda s: (s, 0)),
        name="heat_probe_fixture")
    msgs = _kernel_msgs(call, [_sds((8, _N))])
    assert any(r == "HL404" and "does not divide ref shape" in m
               for r, m in msgs)


def test_hl404_index_map_out_of_range_caught():
    call = pl.pallas_call(
        _zeros_kernel, out_shape=_sds((8, _N)), grid=(2,),
        in_specs=[pl.BlockSpec((4, _N), lambda s: (s + 1, 0))],
        out_specs=pl.BlockSpec((4, _N), lambda s: (s, 0)),
        name="heat_probe_fixture")
    msgs = _kernel_msgs(call, [_sds((8, _N))])
    assert any(r == "HL404" and "outside the" in m for r, m in msgs)


def test_hl404_uncovered_output_blocks_caught():
    call = pl.pallas_call(
        _zeros_kernel, out_shape=_sds((8, _N)), grid=(1,),
        in_specs=[pl.BlockSpec((4, _N), lambda s: (s, 0))],
        out_specs=pl.BlockSpec((4, _N), lambda s: (s, 0)),
        name="heat_probe_fixture")
    msgs = _kernel_msgs(call, [_sds((8, _N))])
    assert any(r == "HL404" and "never visited" in m for r, m in msgs)


def test_hl404_exact_tiling_clean():
    call = pl.pallas_call(
        _zeros_kernel, out_shape=_sds((8, _N)), grid=(2,),
        in_specs=[pl.BlockSpec((4, _N), lambda s: (s, 0))],
        out_specs=pl.BlockSpec((4, _N), lambda s: (s, 0)),
        name="heat_probe_fixture")
    assert _kernel_msgs(call, [_sds((8, _N))]) == []


# ---------------------------------------------------------------------------
# Layer registry
# ---------------------------------------------------------------------------

def test_layer_registry_partitions_all_rules():
    # Every rule lives in exactly one layer, and layer_of agrees.
    seen = {}
    for name, (table, _run) in LAYERS.items():
        for rid in table:
            assert rid not in seen, f"{rid} in both {seen.get(rid)} and {name}"
            seen[rid] = name
    assert set(seen) == set(ALL_RULES)
    assert layer_of("HL101") == "trace"
    assert layer_of("HL205") == "ast"
    assert layer_of("HL301") == "spmd"
    assert layer_of("HL404") == "kernels"


# ---------------------------------------------------------------------------
# Baseline plumbing
# ---------------------------------------------------------------------------

def _finding(rule="HL205", file="pkg/m.py", symbol="<module>"):
    return Finding(rule, "error", file, 3, symbol, "msg")


def test_baseline_suppression_and_stale(tmp_path):
    bl = Baseline(entries={
        ("HL205", "pkg/m.py", "<module>"): "kept: re-export",
        ("HL203", "pkg/gone.py", "build"): "kept: historical",
    })
    active, stale = apply_baseline([_finding(), _finding(file="pkg/n.py")],
                                   bl)
    assert [f.file for f in active] == ["pkg/n.py"]
    assert stale == [("HL203", "pkg/gone.py", "build")]


def test_baseline_path_scope_limits_staleness():
    # Path-scoped stale-ness: an entry of a path-scoped rule is stale
    # only when its file was inside the scanned roots; files outside
    # the scope are unassessed (their violation may still be alive).
    # Non-path-scoped rules (trace/spmd/kernels) ignore the scope.
    bl = Baseline(entries={
        ("HL205", "pkg/scanned.py", "<module>"): "kept: in scope",
        ("HL205", "other/unscanned.py", "<module>"): "kept: out of scope",
        ("HL301", "whole/audit.py", "<audit>"): "kept: not path-scoped",
    })
    active, stale = apply_baseline(
        [], bl, assessed_rules={"HL205", "HL301"},
        assessed_paths=("pkg",), path_rules=frozenset({"HL205"}))
    assert active == []
    assert set(stale) == {("HL205", "pkg/scanned.py", "<module>"),
                          ("HL301", "whole/audit.py", "<audit>")}
    # no scope (default full run): everything assessed is stale
    _, stale_full = apply_baseline(
        [], bl, assessed_rules={"HL205", "HL301"},
        path_rules=frozenset({"HL205"}))
    assert len(stale_full) == 3


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "HL205", "file": "m.py", "symbol": "<module>",
         "justification": "  "}]}))
    with pytest.raises(ValueError, match="empty justification"):
        load_baseline(str(p))


def test_baseline_version_and_missing_file(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(ValueError, match="unsupported version"):
        load_baseline(str(p))
    with pytest.raises(FileNotFoundError):
        load_baseline(str(tmp_path / "absent.json"))


def test_gates_thresholds():
    fs = [Finding("HL205", "warning", "m.py", 1, "<module>", "msg")]
    assert not gates(fs, "error")
    assert gates(fs, "warning")
    assert gates(fs, "info")


def test_to_dict_carries_soundness():
    # A soundness sentinel ("the audit could not run") must stay
    # distinguishable from an ordinary violation of the same rule in
    # machine output; clean findings omit the key entirely.
    plain = _finding().to_dict()
    assert "soundness" not in plain
    sentinel = Finding("HL301", "warning", "pkg/m.py", 0, "<audit>",
                       "mesh skipped", soundness=True)
    assert sentinel.to_dict()["soundness"] is True


# ---------------------------------------------------------------------------
# CLI round-trips (the real tools/heatlint.py as a subprocess)
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=_ROOT):
    return subprocess.run(
        [sys.executable, _HEATLINT, *args], capture_output=True,
        text=True, timeout=300, cwd=cwd,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_cli_list_rules():
    out = _run_cli("--list-rules")
    assert out.returncode == 0
    for rid in ALL_RULES:
        assert rid in out.stdout


def test_cli_unknown_rule_is_usage_error():
    out = _run_cli("--rules", "HL999")
    assert out.returncode == 1
    assert "unknown rule" in out.stderr


def test_cli_seeded_violation_gates_and_baseline_suppresses(tmp_path):
    _fixture(tmp_path, "seeded.py", """
        import os

        def build(kernel, pl, jax):
            return pl.pallas_call(
                kernel, out_shape=jax.ShapeDtypeStruct((8, 8), "float32"))
    """)
    out = _run_cli("--layer", "ast", "--no-baseline", str(tmp_path))
    assert out.returncode == 2
    assert "[HL203/error]" in out.stdout and "[HL205/error]" in out.stdout

    doc = _run_cli("--layer", "ast", "--no-baseline", "--json",
                   str(tmp_path))
    findings = json.loads(doc.stdout)["findings"]
    assert {f["rule"] for f in findings} == {"HL203", "HL205"}

    # Baseline both findings (fixtures live outside the repo, so the
    # match key is the absolute path) -> exits 0; then fix the code ->
    # the entries go stale (warning, not a gate).
    rel = str(tmp_path / "seeded.py")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "HL203", "file": rel, "symbol": "build",
         "justification": "probe kernel, profiler name irrelevant"},
        {"rule": "HL205", "file": rel, "symbol": "<module>",
         "justification": "kept for doctest"}]}))
    out = _run_cli("--layer", "ast", "--baseline", str(bl), str(tmp_path))
    assert out.returncode == 0, out.stdout + out.stderr

    (tmp_path / "seeded.py").write_text("x = 1\n")
    out = _run_cli("--layer", "ast", "--baseline", str(bl), str(tmp_path))
    assert out.returncode == 0
    assert out.stdout.count("stale baseline entry") == 2


def test_cli_rule_subset(tmp_path):
    _fixture(tmp_path, "seeded.py", "import os\n")
    out = _run_cli("--layer", "ast", "--no-baseline", "--rules", "HL203",
                   str(tmp_path))
    assert out.returncode == 0  # HL205 finding filtered out
    out = _run_cli("--layer", "ast", "--no-baseline", "--rules", "HL205",
                   str(tmp_path))
    assert out.returncode == 2


def test_cli_repo_tree_is_clean():
    """The acceptance gate: `tools/heatlint.py --fail-on error` exits 0
    on the repo's own tree (`make lint`)."""
    out = _run_cli("--fail-on", "error")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 error(s)" in out.stdout


def test_cli_works_from_any_cwd(tmp_path):
    """The default scan scope and baseline are anchored to the repo
    root, not the invoker's cwd — a gate run off-root must scan the
    real tree (proven by it finding the repo's committed baseline),
    never report clean on an empty scan set."""
    from parallel_heat_tpu.analysis.astlint import (REPO_ROOT,
                                                    default_scan_paths)

    paths = default_scan_paths()
    assert paths and all(os.path.isabs(p) and p.startswith(REPO_ROOT)
                         for p in paths)
    out = _run_cli("--layer", "ast", "--fail-on", "error",
                   cwd=str(tmp_path))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "heatlint.baseline.json" in out.stdout  # repo ledger found


# ---------------------------------------------------------------------------
# Service-layer coverage (ISSUE 8): heatd rides the HL2xx gate
# ---------------------------------------------------------------------------

def test_ast_scan_covers_service_package():
    """`parallel_heat_tpu/service/` must be inside the default AST
    scan scope — the queue daemon's lock/journal discipline (notably
    HL204 on thread-shared state) is gated, not just reviewed — and
    the tree must be clean with the baseline ledger empty."""
    from parallel_heat_tpu.analysis.astlint import (
        REPO_ROOT,
        _iter_py_files,
        default_scan_paths,
        lint_paths,
    )

    scanned = set(_iter_py_files(default_scan_paths()))
    svc = os.path.join(REPO_ROOT, "parallel_heat_tpu", "service")
    for mod in ("store.py", "daemon.py", "worker.py", "admission.py",
                "client.py", "cli.py", "cache.py", "harness.py",
                "fleet.py"):
        assert os.path.join(svc, mod) in scanned, mod
    assert os.path.join(REPO_ROOT, "tools", "heatq.py") in scanned
    findings = lint_paths([svc])
    assert [f for f in findings if f.severity == "error"] == []


def test_ast_scan_covers_ensemble_package():
    """`parallel_heat_tpu/ensemble/` (+ the batched kernel module)
    rides the HL2xx gate like the service layer — and the tree stays
    clean with the baseline ledger empty (ISSUE 9)."""
    from parallel_heat_tpu.analysis.astlint import (
        REPO_ROOT,
        _iter_py_files,
        default_scan_paths,
        lint_paths,
    )

    scanned = set(_iter_py_files(default_scan_paths()))
    ens = os.path.join(REPO_ROOT, "parallel_heat_tpu", "ensemble")
    for mod in ("engine.py", "checkpoint.py", "supervised.py"):
        assert os.path.join(ens, mod) in scanned, mod
    batched = os.path.join(REPO_ROOT, "parallel_heat_tpu", "ops",
                           "batched.py")
    assert batched in scanned
    findings = lint_paths([ens, batched])
    assert [f for f in findings if f.severity == "error"] == []


def test_ast_scan_covers_coordinator_module():
    """`parallel/coordinator.py` (the distributed-supervision
    consensus layer, ISSUE 10) rides the HL2xx gate — notably HL204 on
    its heartbeat-thread-shared state — and the tree stays clean with
    the baseline ledger empty."""
    from parallel_heat_tpu.analysis.astlint import (
        REPO_ROOT,
        _iter_py_files,
        default_scan_paths,
        lint_paths,
    )

    coord = os.path.join(REPO_ROOT, "parallel_heat_tpu", "parallel",
                         "coordinator.py")
    assert coord in set(_iter_py_files(default_scan_paths()))
    findings = lint_paths([coord])
    assert [f for f in findings if f.severity == "error"] == []


def test_ast_scan_covers_tracing_and_slo_tools():
    """The heattrace plane (ISSUE 12) rides the HL2xx gate like every
    other subsystem: `utils/tracing.py` and the new tools are inside
    the default scan set and lint clean with the ledger empty."""
    from parallel_heat_tpu.analysis.astlint import (
        REPO_ROOT,
        _iter_py_files,
        default_scan_paths,
        lint_paths,
    )

    mods = [os.path.join(REPO_ROOT, "parallel_heat_tpu", "utils",
                         "tracing.py"),
            os.path.join(REPO_ROOT, "tools", "heattrace.py"),
            os.path.join(REPO_ROOT, "tools", "slo_gate.py")]
    scanned = set(_iter_py_files(default_scan_paths()))
    for m in mods:
        assert m in scanned, m
    findings = lint_paths(mods)
    assert [f for f in findings if f.severity == "error"] == []
