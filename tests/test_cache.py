"""The content-addressed result cache (SEMANTICS.md "Cache
soundness"): key partition discipline, the index journal's fold law,
the admissibility matrix, LRU eviction, and the daemon's exact/prefix
serve paths with client provenance round-trips.

Everything except the two inline end-to-end tests runs jax-free on
fake entries and tmp dirs — the admissibility rules are pure functions
and are tested as such. The bitwise proof obligation of prefix resume
is pinned here at 16x16 and certified at the chaos level by
``tools/chaos_matrix.py`` cell ``svc_cache_prefix_parity``.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from parallel_heat_tpu.config import (
    OBSERVATION_ONLY_FIELDS,
    SEMANTIC_FIELDS,
    HeatConfig,
)
from parallel_heat_tpu.service import cache as C
from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
from parallel_heat_tpu.service.store import JobSpec, JobStore

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Key derivation: the SEMANTIC_FIELDS partition IS the cache key
# ---------------------------------------------------------------------------

def test_cache_key_ignores_observation_only_fields():
    # The HL101 discipline applied to serving: enabling an observer
    # must not fork (or miss) a cache entry.
    base = {"nx": 16, "ny": 16, "steps": 60}
    k1, _ = C.cache_key(base)
    k2, _ = C.cache_key({**base, "guard_interval": 5,
                         "diag_interval": 10, "pipeline_depth": 2})
    assert k1 == k2


def test_cache_key_moves_with_every_semantic_field():
    base, _ = C.cache_key({"nx": 16, "ny": 16, "steps": 60})
    moved = {
        "nx": 17, "ny": 17, "nz": 4, "cx": 0.2, "cy": 0.2, "cz": 0.2,
        "steps": 61, "converge": True, "eps": 1e-4,
        "check_interval": 7, "dtype": "bfloat16", "backend": "jnp",
        "mesh_shape": [2, 1], "overlap": False, "halo_depth": 2,
        "halo_overlap": "phase", "accumulate": "f32chunk",
        "scheme": "backward_euler", "mg_tol": 1e-5, "mg_cycles": 7,
        "mg_smooth": 2, "mg_levels": 3, "mg_partition": "partitioned",
    }
    assert set(moved) == set(SEMANTIC_FIELDS)
    for field, value in moved.items():
        k, _ = C.cache_key({"nx": 16, "ny": 16, "steps": 60,
                            field: value})
        assert k != base, f"semantic field {field!r} did not move the key"


def test_cache_key_defaults_are_canonical():
    # Spelling a default explicitly cannot fork an entry.
    k1, _ = C.cache_key({"nx": 16, "ny": 16, "steps": 60})
    k2, _ = C.cache_key({"nx": 16, "ny": 16, "steps": 60,
                         "backend": "auto", "overlap": True})
    assert k1 == k2


def test_cache_key_unclassified_field_fails_like_hl101():
    # A new HeatConfig field in NEITHER partition tuple must fail key
    # derivation loudly — the exact condition heatlint HL101 fails CI
    # on, enforced independently at the serving layer.
    @dataclasses.dataclass(frozen=True)
    class Doctored(HeatConfig):
        sneaky_new_field: int = 0

    with pytest.raises(C.CacheKeyError, match="sneaky_new_field"):
        C.cache_key({"nx": 16}, config_cls=Doctored)
    with pytest.raises(C.CacheKeyError, match="HL101"):
        C.cache_key({"nx": 16}, config_cls=Doctored)


def test_cache_key_double_classified_field_fails():
    with pytest.raises(C.CacheKeyError, match="double-classified"):
        C.cache_key({"nx": 16},
                    semantic=SEMANTIC_FIELDS + ("guard_interval",),
                    observation=OBSERVATION_ONLY_FIELDS)


def test_cache_key_unknown_field_refuses():
    with pytest.raises(C.CacheKeyError, match="not_a_field"):
        C.cache_key({"nx": 16, "not_a_field": 1})


def test_base_key_excludes_exactly_the_stepping_fields():
    b = C.base_key({"nx": 16, "ny": 16, "steps": 60})
    assert C.base_key({"nx": 16, "ny": 16, "steps": 600,
                       "converge": True, "eps": 1e-9,
                       "check_interval": 5}) == b
    assert C.base_key({"nx": 16, "ny": 16, "steps": 60,
                       "dtype": "bfloat16"}) != b


def test_partition_tuples_cover_heatconfig():
    # The pin the doctored-subclass test relies on: the REAL config is
    # fully classified, so key derivation never raises in production.
    names = {f.name for f in dataclasses.fields(HeatConfig)}
    assert names == set(SEMANTIC_FIELDS) | set(OBSERVATION_ONLY_FIELDS)
    assert set(C.STEPPING_FIELDS) <= set(SEMANTIC_FIELDS)


# ---------------------------------------------------------------------------
# Index journal fold law
# ---------------------------------------------------------------------------

def _put(key, base="b", t=1000.0, **kw):
    e = {"event": "cache_put", "key": key, "base": base, "t_wall": t,
         "job_id": kw.pop("job_id", f"donor-{key}"), "attempt": 1,
         "steps": 60, "converge": False, "eps": 1e-3,
         "check_interval": 20, "steps_done": 60,
         "generations": [20, 40, 60], "bytes": 100,
         "payload": f"/p/{key}"}
    e.update(kw)
    return e


def test_reduce_cache_journal_fold_law():
    events = [
        _put("k1", t=1.0), _put("k2", t=2.0),
        {"event": "cache_touch", "key": "k1", "t_wall": 3.0},
        {"event": "cache_touch", "key": "k2", "t_wall": 4.0,
         "kind": "prefix"},
        {"event": "cache_evict", "key": "k1"},
        _put("k3", t=5.0),
    ]
    whole = C.reduce_cache_journal(events)
    for cut in range(len(events) + 1):
        state = C.reduce_cache_journal(events[:cut])
        folded = C.reduce_cache_journal(events[cut:], state=state)
        assert folded == whole
    entries, anomalies = whole
    assert set(entries) == {"k2", "k3"}
    assert entries["k2"]["prefix_hits"] == 1
    assert entries["k2"]["last_used_t"] == 4.0
    assert anomalies == []


def test_reduce_cache_journal_unknown_key_anomalies():
    _, anomalies = C.reduce_cache_journal([
        {"event": "cache_touch", "key": "ghost", "t_wall": 1.0},
        {"event": "cache_evict", "key": "ghost2"},
    ])
    assert len(anomalies) == 2
    assert "touch of unknown" in anomalies[0]
    assert "evict of unknown" in anomalies[1]


def test_reduce_cache_journal_put_replaces_and_reput_after_evict():
    entries, anomalies = C.reduce_cache_journal([
        _put("k1", t=1.0, steps_done=60),
        _put("k1", t=2.0, steps_done=60, bytes=200),
        {"event": "cache_evict", "key": "k1"},
        _put("k1", t=3.0),
    ])
    assert anomalies == []
    assert entries["k1"]["put_t"] == 3.0
    # post-evict re-put starts fresh (the old usage died with the
    # entry)
    assert entries["k1"]["hits"] == 0
    assert entries["k1"]["last_used_t"] == 3.0


def test_reduce_cache_journal_reput_of_live_key_keeps_usage():
    # Two twins dispatched before either completed: the second
    # completion re-puts the same content address. The entry's LRU
    # recency and hit counters must survive, or a hot entry gets
    # evicted ahead of cold ones.
    entries, anomalies = C.reduce_cache_journal([
        _put("k1", t=1.0),
        {"event": "cache_touch", "key": "k1", "t_wall": 50.0},
        {"event": "cache_touch", "key": "k1", "t_wall": 51.0,
         "kind": "prefix"},
        _put("k1", t=2.0, job_id="twin"),
    ])
    assert anomalies == []
    assert entries["k1"]["hits"] == 1
    assert entries["k1"]["prefix_hits"] == 1
    assert entries["k1"]["last_used_t"] == 50.0 + 1.0
    assert entries["k1"]["job_id"] == "twin"  # content refreshed


def test_reduce_cache_journal_ignores_foreign_lines():
    entries, anomalies = C.reduce_cache_journal([
        {"event": "mystery_event", "key": "k1"},
        {"event": "cache_put"},  # no key
        {"not": "an event"},
    ])
    assert entries == {} and anomalies == []


# ---------------------------------------------------------------------------
# Admissibility (pure lookups over fake entries; fake clocks)
# ---------------------------------------------------------------------------

_FIXED60 = {"nx": 16, "ny": 16, "steps": 60}


def _entry_for(config, steps_done, converged=None, gens=None,
               job_id="donor", t=1000.0):
    key, canon = C.cache_key(config)
    return _put(key, base=C.base_key(config), t=t, job_id=job_id,
                steps=canon["steps"], converge=canon["converge"],
                eps=canon["eps"], check_interval=canon["check_interval"],
                scheme=canon.get("scheme"),
                steps_done=steps_done, converged=converged,
                generations=gens or [steps_done])


def _entries(*events):
    entries, anomalies = C.reduce_cache_journal(list(events))
    assert anomalies == []
    return entries

def test_lookup_exact_same_key():
    entries = _entries(_entry_for(_FIXED60, 60))
    hit = C.lookup_exact(entries, dict(_FIXED60, guard_interval=5))
    assert hit is not None and hit[1] == "exact"


def test_lookup_exact_misses_without_final_generation():
    # An entry whose newest retained generation is not the committed
    # result (should not exist by the put gate, but the lookup must
    # not trust it) cannot serve O(1).
    entries = _entries(_entry_for(_FIXED60, 60, gens=[20, 40]))
    assert C.lookup_exact(entries, _FIXED60) is None


def test_lookup_exact_converged_dominance():
    conv = {"nx": 16, "ny": 16, "steps": 100, "converge": True,
            "eps": 1e-2, "check_interval": 10}
    entries = _entries(_entry_for(conv, 40, converged=True))
    # Larger budget, same eps/cadence: the scratch run converges at
    # the donor's window with the donor's grid.
    hit = C.lookup_exact(entries, dict(conv, steps=400))
    assert hit is not None and hit[1] == "converged"
    # Budget BELOW the convergence step: the scratch run would stop
    # unconverged at 30 — a different grid; must miss.
    assert C.lookup_exact(entries, dict(conv, steps=30)) is None
    # Different eps: different verdict sequence; must miss.
    assert C.lookup_exact(entries, dict(conv, steps=400,
                                        eps=2e-2)) is None
    # A fixed target never takes a converged-dominance serve.
    assert C.lookup_exact(entries, dict(_FIXED60, steps=400)) is None


def test_lookup_prefix_fixed_extension():
    entries = _entries(_entry_for(_FIXED60, 60, gens=[20, 40, 60]))
    entry, gen = C.lookup_prefix(entries, dict(_FIXED60, steps=120))
    assert gen == 60
    # Equal budget is the exact path's job, not a prefix.
    assert C.lookup_prefix(entries, _FIXED60) == (entry, 40)


def test_lookup_prefix_picks_newest_admissible_generation():
    e1 = _entry_for(_FIXED60, 60, gens=[20, 40, 60], job_id="d1")
    e2 = _entry_for(dict(_FIXED60, steps=200), 200,
                    gens=[100, 150, 200], job_id="d2")
    entries = _entries(e1, e2)
    _, gen = C.lookup_prefix(entries, dict(_FIXED60, steps=180))
    assert gen == 150  # 200 is past the budget; 150 beats 60
    # Converge donors' generations serve fixed targets too — the
    # trajectory is the same stepping (the cross-arm is sound this
    # direction: stopping verdicts don't exist in fixed mode).
    e3 = _entry_for(dict(_FIXED60, steps=400, converge=True,
                         eps=1e-9, check_interval=10),
                    400, converged=False, gens=[300, 350, 400],
                    job_id="d3")
    entries = _entries(e1, e2, e3)
    _, gen = C.lookup_prefix(entries, dict(_FIXED60, steps=390))
    assert gen == 350


def test_lookup_prefix_semantic_mismatch_never_crosses():
    entries = _entries(_entry_for(_FIXED60, 60, gens=[20, 40, 60]))
    for delta in ({"dtype": "bfloat16"}, {"cx": 0.2},
                  {"nx": 32, "ny": 32}):
        target = dict(_FIXED60, steps=120, **delta)
        assert C.lookup_prefix(entries, target) is None, delta


def test_cache_key_unclassified_scheme_field_fails_like_hl101():
    # The satellite contract (SEMANTICS.md "Implicit stepping"): a
    # NEW scheme-adjacent config field that joins neither partition
    # tuple must fail key derivation loudly — a doctored subclass
    # sneaking an unclassified solver knob past HL101 cannot silently
    # key (or silently ignore) it at the serving layer.
    @dataclasses.dataclass(frozen=True)
    class DoctoredScheme(HeatConfig):
        mg_omega: float = 0.8  # a plausible-looking unclassified knob

    with pytest.raises(C.CacheKeyError, match="mg_omega"):
        C.cache_key({"nx": 16, "scheme": "backward_euler"},
                    config_cls=DoctoredScheme)


def test_cross_scheme_reuse_declines_both_directions():
    # Explicit donor must serve NOTHING to an implicit target, and
    # vice versa — the schemes compute different trajectories
    # (SEMANTICS.md "Implicit stepping": the admissibility table's
    # first row). Structurally the scheme sits in the base key, so
    # both lookups miss without any entry even being scheme-checked.
    stiff = {"nx": 16, "ny": 16, "steps": 60, "cx": 2.0, "cy": 2.0,
             "scheme": "backward_euler"}
    explicit_donor = _entry_for({**stiff, "scheme": "explicit"}, 60,
                                gens=[20, 40, 60], job_id="exp")
    implicit_donor = _entry_for(stiff, 60, gens=[20, 40, 60],
                                job_id="imp")
    entries = _entries(explicit_donor, implicit_donor)
    # Exact: each target hits only its own scheme's entry.
    hit = C.lookup_exact(entries, stiff)
    assert hit is not None and hit[0]["job_id"] == "imp"
    hit = C.lookup_exact(entries, {**stiff, "scheme": "explicit"})
    assert hit is not None and hit[0]["job_id"] == "exp"
    # Prefix: extensions resume only from the same-scheme donor.
    entry, gen = C.lookup_prefix(entries, dict(stiff, steps=120))
    assert entry["job_id"] == "imp" and gen == 60
    entry, _ = C.lookup_prefix(
        entries, dict(stiff, steps=120, scheme="explicit"))
    assert entry["job_id"] == "exp"
    # A lone cross-scheme donor serves nothing at all.
    only_explicit = _entries(explicit_donor)
    assert C.lookup_exact(only_explicit, stiff) is None
    assert C.lookup_prefix(only_explicit,
                           dict(stiff, steps=120)) is None
    only_implicit = _entries(implicit_donor)
    explicit_target = {**stiff, "scheme": "explicit"}
    assert C.lookup_exact(only_implicit, explicit_target) is None
    assert C.lookup_prefix(only_implicit,
                           dict(explicit_target, steps=120)) is None
    # mg solver knobs are semantic too: a different mg_tol is a
    # different trajectory family — no reuse.
    assert C.lookup_prefix(
        entries, dict(stiff, steps=120, mg_tol=1e-5)) is None


def test_cross_scheme_decline_survives_forged_base_collision():
    # Defense in depth (cache.py::_scheme_match): even an index line
    # FORGED to carry the other scheme's base key — a collision the
    # content address makes cryptographically implausible, a
    # hand-edited journal does not — must not cross the scheme wall,
    # because the lookups re-check the donor's recorded scheme.
    stiff = {"nx": 16, "ny": 16, "steps": 60, "cx": 2.0, "cy": 2.0,
             "scheme": "backward_euler"}
    forged = _entry_for({**stiff, "scheme": "explicit"}, 60,
                        gens=[20, 40, 60], job_id="forged")
    forged["base"] = C.base_key(stiff)  # the lie
    entries = _entries(forged)
    assert C.lookup_prefix(entries, dict(stiff, steps=120)) is None
    # Converged-dominance arm re-checks too.
    conv = dict(stiff, converge=True, eps=1e-2, check_interval=10,
                steps=100)
    forged2 = _entry_for({**conv, "scheme": "explicit"}, 40,
                         converged=True, job_id="forged2")
    forged2["base"] = C.base_key(conv)
    assert C.lookup_exact(_entries(forged2),
                          dict(conv, steps=400)) is None
    # Pre-scheme index lines (scheme unrecorded) remain valid
    # explicit donors — None means "explicit by construction".
    legacy = _entry_for({k: v for k, v in stiff.items()
                         if k != "scheme"}, 60, gens=[20, 40, 60],
                        job_id="legacy")
    legacy.pop("scheme", None)
    entry, gen = C.lookup_prefix(
        _entries(legacy),
        {k: v for k, v in dict(stiff, steps=120).items()
         if k != "scheme"})
    assert entry["job_id"] == "legacy" and gen == 60


def test_lookup_prefix_converge_needs_unconverged_donor():
    conv = {"nx": 16, "ny": 16, "steps": 40, "converge": True,
            "eps": 1e-9, "check_interval": 10}
    exhausted = _entry_for(conv, 40, converged=False,
                           gens=[20, 30, 40], job_id="ex")
    entries = _entries(exhausted)
    entry, gen = C.lookup_prefix(entries, dict(conv, steps=80))
    assert gen == 40
    # A CONVERGED donor has a verdict inside its window sequence —
    # nothing sound to resume past for a converge target.
    converged = _entry_for(dict(conv, steps=100), 40, converged=True,
                           gens=[20, 30, 40], job_id="cv")
    entries = _entries(converged)
    assert C.lookup_prefix(entries, dict(conv, steps=80)) is None
    # Cadence must match: eps or check_interval off by anything kills
    # the verdict-alignment argument.
    entries = _entries(exhausted)
    assert C.lookup_prefix(entries, dict(conv, steps=80,
                                         eps=1e-8)) is None
    assert C.lookup_prefix(entries, dict(conv, steps=80,
                                         check_interval=20)) is None


def test_lookup_prefix_fixed_donor_converge_target_needs_evidence():
    fixed = _entry_for(dict(_FIXED60, steps=200), 200,
                       gens=[100, 150, 200], job_id="fx")
    conv_target = {"nx": 16, "ny": 16, "steps": 400, "converge": True,
                   "eps": 1e-9, "check_interval": 10}
    # No converge entry proves non-convergence: the scratch run might
    # stop before any donor generation — MUST decline (the bitwise
    # contract is the acceptance criterion, not best-effort reuse).
    assert C.lookup_prefix(_entries(fixed), conv_target) is None
    # An unconverged converge sibling through step 120 licenses
    # generations <= 120 — so gen 100, not the newer 150/200.
    evidence = _entry_for(dict(conv_target, steps=120), 120,
                          converged=False, gens=[100, 110, 120],
                          job_id="ev")
    entries = _entries(fixed, evidence)
    entry, gen = C.lookup_prefix(entries, conv_target)
    assert gen == 120  # the evidence entry's own newest window
    # Strictly-later convergence is evidence too (no verdict BEFORE
    # it), licensing the fixed donor's 150 (< 160) but not 200.
    conv_late = _entry_for(dict(conv_target, steps=300), 160,
                           converged=True, gens=[140, 150, 160],
                           job_id="cl")
    entries = _entries(fixed, conv_late)
    entry, gen = C.lookup_prefix(entries, conv_target)
    assert (entry["job_id"], gen) == ("fx", 150)


def test_lookup_prefix_alignment_to_check_interval():
    # A converge target may only resume at its own window boundaries:
    # a mid-window start would shift every later verdict step.
    conv = {"nx": 16, "ny": 16, "steps": 80, "converge": True,
            "eps": 1e-9, "check_interval": 25}
    donor = _entry_for(dict(conv, steps=60), 60, converged=False,
                       gens=[40, 50, 60], job_id="dx")
    entries = _entries(donor)
    found = C.lookup_prefix(entries, conv)
    assert found is not None and found[1] == 50  # 60, 40 misalign


# ---------------------------------------------------------------------------
# Eviction policy (fake clocks)
# ---------------------------------------------------------------------------

def test_evict_candidates_lru_order_and_budgets():
    events = [_put(f"k{i}", t=float(i), bytes=100) for i in range(5)]
    events.append({"event": "cache_touch", "key": "k0", "t_wall": 99.0})
    entries = _entries(*events)
    # 500 B held, budget 250: evict oldest-used first — k1, k2, k3
    # (k0 was touched to t=99).
    assert C.evict_candidates(entries, 250, None) == ["k1", "k2", "k3"]
    assert C.evict_candidates(entries, None, 2) == ["k1", "k2", "k3"]
    assert C.evict_candidates(entries, None, None) == []


def test_evict_candidates_pinned_donors_survive():
    entries = _entries(*[_put(f"k{i}", t=float(i), bytes=100)
                         for i in range(3)])
    # k0 (oldest) is pinned: the budget is met by the next-oldest.
    assert C.evict_candidates(entries, None, 2,
                              pinned=["k0"]) == ["k1"]
    assert C.evict_candidates(entries, None, 1,
                              pinned=["k0"]) == ["k1", "k2"]
    # Only pinned entries left: stays over budget rather than evict.
    assert C.evict_candidates(entries, None, 0,
                              pinned=["k0", "k1", "k2"]) == []


# ---------------------------------------------------------------------------
# CacheIndex durability (tmp dirs, no jax)
# ---------------------------------------------------------------------------

def _fake_lineage(tmp_path, job="donor", steps=(20, 40, 60),
                  shape=(4, 4)):
    """A committed gathered-generation family a real run would leave."""
    d = tmp_path / "ck" / job
    d.mkdir(parents=True, exist_ok=True)
    stem = str(d / "ck")
    for s in steps:
        np.savez(f"{stem}.g{s:012d}.npz",
                 grid=np.full(shape, float(s), dtype=np.float32),
                 step=np.int64(s))
    return stem


def test_cache_index_put_lookup_roundtrip(tmp_path):
    idx = C.CacheIndex(str(tmp_path))
    stem = _fake_lineage(tmp_path)
    entry = idx.put(_FIXED60, stem, job_id="donor", attempt=1,
                    steps_done=60)
    assert entry is not None
    assert entry["generations"] == [20, 40, 60]
    assert entry["bytes"] > 0
    # Cold reload folds to the same state (daemon restart).
    entries, anomalies, bad, torn = C.load_cache_index(str(tmp_path))
    assert anomalies == [] and bad == 0 and not torn
    assert entries[entry["key"]]["payload"] == entry["payload"]
    hit = C.lookup_exact(entries, _FIXED60)
    assert hit is not None
    idx.close()


def test_cache_index_put_declines_nonfinite_and_stale(tmp_path):
    idx = C.CacheIndex(str(tmp_path))
    stem = _fake_lineage(tmp_path, job="bad")
    np.savez(f"{stem}.g{60:012d}.npz",
             grid=np.full((4, 4), np.nan, dtype=np.float32),
             step=np.int64(60))
    assert idx.put(_FIXED60, stem, job_id="bad", attempt=1,
                   steps_done=60) is None  # non-finite result
    stem2 = _fake_lineage(tmp_path, job="stale", steps=(20, 40))
    assert idx.put(_FIXED60, stem2, job_id="stale", attempt=1,
                   steps_done=60) is None  # newest gen != steps_done
    assert idx.put(_FIXED60, str(tmp_path / "nothing" / "ck"),
                   job_id="none", attempt=1, steps_done=60) is None
    assert idx.entries() == {}
    idx.close()


def test_cache_index_evict_then_sweep(tmp_path):
    idx = C.CacheIndex(str(tmp_path))
    stem = _fake_lineage(tmp_path)
    entry = idx.put(_FIXED60, stem, job_id="donor", attempt=1,
                    steps_done=60)
    payload = entry["payload"]
    assert os.path.isdir(payload)
    idx.evict(entry["key"])
    assert not os.path.isdir(payload)
    assert idx.entries() == {}
    # Orphan payload (the evict-line-then-delete crash window, or a
    # put that never reached its index line): swept, never served.
    os.makedirs(os.path.join(str(tmp_path), "cache", "orphanpayload"))
    assert idx.sweep_orphans() == 1
    idx.close()


def test_cache_index_torn_tail_invisible(tmp_path):
    idx = C.CacheIndex(str(tmp_path))
    stem = _fake_lineage(tmp_path)
    idx.put(_FIXED60, stem, job_id="donor", attempt=1, steps_done=60)
    idx.close()
    with open(os.path.join(str(tmp_path), "cache", "index.jsonl"),
              "a") as f:
        f.write('{"event": "cache_put", "key": "torn')  # no newline
    entries, anomalies, bad, torn = C.load_cache_index(str(tmp_path))
    assert len(entries) == 1 and anomalies == [] and bad == 0 and torn


def test_seed_stem_and_marker_roundtrip(tmp_path):
    idx = C.CacheIndex(str(tmp_path))
    stem = _fake_lineage(tmp_path)
    entry = idx.put(_FIXED60, stem, job_id="donor", attempt=1,
                    steps_done=60)
    dst = str(tmp_path / "ck" / "newjob" / "ck")
    marker = {"key": entry["key"], "donor": "donor",
              "generation_step": 40}
    seeded = C.seed_stem(entry, 40, dst, marker=marker)
    assert seeded == f"{dst}.g{40:012d}.npz"
    with np.load(seeded) as z:
        assert float(z["grid"][0, 0]) == 40.0
    assert C.read_seed_marker(dst) == marker
    # Missing generation -> None, caller solves from scratch.
    assert C.seed_stem(entry, 99, dst) is None
    idx.close()


# ---------------------------------------------------------------------------
# Durability audit (heatq --check's cache half)
# ---------------------------------------------------------------------------

def _audit_fixture(tmp_path):
    root = str(tmp_path)
    store = JobStore(root)
    idx = C.CacheIndex(root)
    stem = _fake_lineage(tmp_path, job="donor")
    store.write_result("donor", 1, {"outcome": "completed",
                                    "job_id": "donor",
                                    "steps_done": 60})
    entry = idx.put(_FIXED60, stem, job_id="donor", attempt=1,
                    steps_done=60)
    idx.close()
    store.close()
    return root, entry


def test_audit_cache_clean(tmp_path):
    root, _ = _audit_fixture(tmp_path)
    entries, anomalies, _, _ = C.load_cache_index(root)
    assert anomalies == []
    assert C.audit_cache(root, entries) == []


def test_audit_cache_dangling_entry(tmp_path):
    import shutil

    root, entry = _audit_fixture(tmp_path)
    shutil.rmtree(entry["payload"])
    entries, _, _, _ = C.load_cache_index(root)
    anoms = C.audit_cache(root, entries)
    assert len(anoms) == 1 and "dangling" in anoms[0]
    # a missing generation FILE (payload dir present) is dangling too
    root2 = tmp_path / "r2"
    root2.mkdir()
    r2, e2 = _audit_fixture(root2)
    os.unlink(os.path.join(e2["payload"], f"ck.g{40:012d}.npz"))
    entries, _, _, _ = C.load_cache_index(r2)
    anoms = C.audit_cache(r2, entries)
    assert len(anoms) == 1 and "generation 40 missing" in anoms[0]


def test_audit_cache_uncommitted_result(tmp_path):
    root, entry = _audit_fixture(tmp_path)
    os.unlink(os.path.join(root, "results", "donor.a0001.json"))
    entries, _, _, _ = C.load_cache_index(root)
    anoms = C.audit_cache(root, entries)
    assert len(anoms) == 1 and "uncommitted result" in anoms[0]


def test_heatq_check_gates_on_cache_anomalies(tmp_path):
    import shutil
    import subprocess
    import sys

    root, entry = _audit_fixture(tmp_path)
    heatq = os.path.join(_ROOT, "tools", "heatq.py")

    def run():
        return subprocess.run(
            [sys.executable, heatq, root, "--check", "--json"],
            capture_output=True, text=True)

    r = run()
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["cache"]["entries"] == 1
    assert doc["cache"]["anomalies"] == []
    shutil.rmtree(entry["payload"])  # dangling now
    r = run()
    assert r.returncode == 2, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert any("dangling" in a for a in doc["cache"]["anomalies"])


# ---------------------------------------------------------------------------
# Daemon integration: serve paths, provenance, pins (fake clocks where
# no solve is needed; real 16x16 inline solves end-to-end)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _inline_daemon(root, **kw):
    from parallel_heat_tpu.service.harness import inline_launcher

    spawns = []
    kw.setdefault("slots", 1)
    kw.setdefault("requeue_backoff_base_s", 0.0)
    d = Heatd(HeatdConfig(root=str(root),
                          launcher=inline_launcher(str(root), spawns),
                          **kw))
    return d, spawns


def _run_until_terminal(d, jid, passes=40):
    for _ in range(passes):
        d.step()
        jobs, anomalies = d.store.replay()
        if jid in jobs and jobs[jid].terminal:
            return jobs, anomalies
    raise AssertionError(f"{jid} never terminal: {jobs.get(jid)}")


def _spec(jid, steps=60, **cfg_kw):
    cfg = {"nx": 16, "ny": 16, "steps": steps, "backend": "jnp"}
    cfg.update(cfg_kw)
    return JobSpec(job_id=jid, config=cfg, checkpoint_every=20)


def test_end_to_end_exact_hit_zero_spawns_with_provenance(tmp_path):
    from parallel_heat_tpu import HeatConfig as HC
    from parallel_heat_tpu import solve
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
    )

    d, spawns = _inline_daemon(tmp_path / "q")
    d.store.spool_submit(_spec("cold"))
    _run_until_terminal(d, "cold")
    d.store.spool_submit(_spec("warm"))
    jobs, anomalies = _run_until_terminal(d, "warm")
    assert anomalies == []
    assert spawns == ["cold"]  # ZERO spawns for the warm submit
    v = jobs["warm"]
    assert v.state == "completed" and v.steps_done == 60
    assert v.attempts == 0  # no dispatch ever journaled
    assert v.cached == {"hit": "exact",
                        "key": v.cached["key"],
                        "donor": "cold", "generation_step": 60}
    # provenance in the rename-committed result record too
    rec = d.store.read_result("warm", 0)
    assert rec["outcome"] == "completed"
    assert rec["cache"]["donor"] == "cold"
    # the served job's lineage is on disk, bitwise the real solve
    cfg = HC(nx=16, ny=16, steps=60, backend="jnp")
    grid, step, _ = load_checkpoint(
        latest_checkpoint(d.store.checkpoint_stem("warm")), cfg)
    assert step == 60
    np.testing.assert_array_equal(np.asarray(grid),
                                  solve(cfg).to_numpy())
    # the accepted line priced zero HBM (nothing will run)
    events, _, _ = d.store.read_journal()
    accepted = [e for e in events if e.get("event") == "accepted"
                and e.get("job_id") == "warm"]
    assert accepted[0]["hbm_bytes"] == 0
    d.close()


def test_end_to_end_prefix_resume_bitwise(tmp_path):
    from parallel_heat_tpu import HeatConfig as HC
    from parallel_heat_tpu import solve
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
    )

    d, spawns = _inline_daemon(tmp_path / "q")
    d.store.spool_submit(_spec("short"))
    _run_until_terminal(d, "short")
    d.store.spool_submit(_spec("long", steps=120))
    jobs, anomalies = _run_until_terminal(d, "long")
    assert anomalies == []
    assert spawns == ["short", "long"]  # prefix still runs a worker
    events, _, _ = d.store.read_journal()
    pre = [e for e in events if e.get("event") == "cache_prefix"]
    assert len(pre) == 1
    assert pre[0]["job_id"] == "long"
    assert pre[0]["donor"] == "short"
    assert pre[0]["generation_step"] == 60 == pre[0]["steps_saved"]
    # THE acceptance criterion: bitwise a from-scratch solve.
    cfg = HC(nx=16, ny=16, steps=120, backend="jnp")
    grid, step, _ = load_checkpoint(
        latest_checkpoint(d.store.checkpoint_stem("long")), cfg)
    assert step == 120
    np.testing.assert_array_equal(np.asarray(grid),
                                  solve(cfg).to_numpy())
    # the worker journaled its provenance into the telemetry stream
    with open(d.store.telemetry_path("long")) as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    resumes = [e for e in evs if e.get("event") == "cache_prefix_resume"]
    assert len(resumes) == 1 and resumes[0]["generation_step"] == 60
    d.close()


def test_faulted_specs_bypass_cache_both_ways(tmp_path):
    d, spawns = _inline_daemon(tmp_path / "q")
    # A fault-injected run must not POPULATE the cache...
    d.store.spool_submit(_spec("chaotic",
                               faults={"transient_on_chunks": [1]},
                               faults_on_attempt=2))
    _run_until_terminal(d, "chaotic")
    assert d.cache.entries() == {}
    d.store.spool_submit(_spec("clean"))
    _run_until_terminal(d, "clean")
    assert len(d.cache.entries()) == 1
    # ...and must not be SERVED from it either.
    d.store.spool_submit(_spec("chaotic2",
                               faults={"transient_on_chunks": [1]},
                               faults_on_attempt=2))
    jobs, anomalies = _run_until_terminal(d, "chaotic2")
    assert anomalies == []
    assert jobs["chaotic2"].cached is None
    assert "chaotic2" in spawns
    d.close()


def test_cache_disabled_runs_every_submit(tmp_path):
    d, spawns = _inline_daemon(tmp_path / "q", cache_results=False)
    assert d.cache is None
    for jid in ("a", "b"):
        d.store.spool_submit(_spec(jid))
        jobs, anomalies = _run_until_terminal(d, jid)
    assert spawns == ["a", "b"]
    assert jobs["b"].cached is None
    assert not os.path.exists(os.path.join(str(tmp_path / "q"),
                                           "cache", "index.jsonl"))
    d.close()


def test_eviction_budget_enforced_end_to_end(tmp_path):
    # max_entries=1: completing a second distinct spec evicts the
    # first entry (older LRU stamp) and deletes its payload bytes.
    d, spawns = _inline_daemon(tmp_path / "q", cache_max_entries=1)
    d.store.spool_submit(_spec("a", steps=40))
    _run_until_terminal(d, "a")
    first = dict(d.cache.entries())
    d.store.spool_submit(_spec("b", steps=60))
    _run_until_terminal(d, "b")
    entries = d.cache.entries()
    assert len(entries) == 1
    (key, e), = entries.items()
    assert e["job_id"] == "b"
    old_payload = next(iter(first.values()))["payload"]
    assert not os.path.isdir(old_payload)
    # the evicted spec re-solves instead of serving
    d.store.spool_submit(_spec("a2", steps=40))
    jobs, anomalies = _run_until_terminal(d, "a2")
    assert anomalies == [] and "a2" in spawns
    assert jobs["a2"].cached is None
    d.close()


def test_dispatch_time_hit_for_jobs_queued_before_donor_completed(
        tmp_path):
    # The burst case: twin specs admitted together, slots=1 — the
    # second must serve from the first's completion at DISPATCH time
    # (admission-time lookup saw an empty cache).
    d, spawns = _inline_daemon(tmp_path / "q", slots=1)
    d.store.spool_submit(_spec("t1"))
    d.store.spool_submit(_spec("t2"))
    d.step()  # both admitted; t1 dispatched (inline: completes on poll)
    jobs, anomalies = _run_until_terminal(d, "t2")
    assert anomalies == []
    assert spawns == ["t1"]
    assert jobs["t2"].state == "completed"
    assert (jobs["t2"].cached or {}).get("donor") == "t1"
    d.close()


def test_crash_between_result_and_index_loses_entry_not_job(tmp_path):
    # The svc_cache_crash window, unit-level (the chaos cell does it
    # with a real SIGKILL): journal says completed, cache index says
    # nothing -> a rebuilt daemon re-solves the next identical submit.
    root = tmp_path / "q"
    d, spawns = _inline_daemon(root)
    real_put = d.cache.put
    d.cache.put = lambda *a, **k: None  # the append never happens
    d.store.spool_submit(_spec("j1"))
    jobs, anomalies = _run_until_terminal(d, "j1")
    assert jobs["j1"].state == "completed" and anomalies == []
    d.cache.put = real_put
    d.close()

    d2, spawns2 = _inline_daemon(root)
    assert d2.cache.entries() == {}  # entry lost
    d2.store.spool_submit(_spec("j2"))
    jobs, anomalies = _run_until_terminal(d2, "j2")
    assert anomalies == []
    assert spawns2 == ["j2"]  # re-solved, not served
    assert jobs["j2"].cached is None
    d2.close()


def test_journal_cache_spans_in_heattrace_model():
    # The acceptance criterion's "visible as a cache_hit span":
    # spans_from_journal renders the O(1) serve as a real span
    # (accepted -> verdict) parented under the job, and the prefix
    # line as an instant.
    from parallel_heat_tpu.utils.tracing import (
        chrome_trace,
        spans_from_journal,
        submit_span_id,
    )

    events = [
        {"event": "accepted", "job_id": "w", "t_wall": 10.0,
         "trace_id": "t-1"},
        {"event": "cache_hit", "job_id": "w", "t_wall": 10.01,
         "key": "k", "kind": "exact", "donor": "d",
         "generation_step": 60, "steps_saved": 60,
         "bytes_saved": 1234, "trace_id": "t-1"},
        {"event": "completed", "job_id": "w", "t_wall": 10.02,
         "steps_done": 60,
         "cache": {"hit": "exact", "key": "k", "donor": "d"}},
        {"event": "accepted", "job_id": "p", "t_wall": 11.0},
        {"event": "cache_prefix", "job_id": "p", "t_wall": 11.01,
         "key": "k", "donor": "d", "generation_step": 60},
        {"event": "dispatched", "job_id": "p", "t_wall": 11.02,
         "worker": "w-p-a001", "attempt": 1},
        {"event": "completed", "job_id": "p", "t_wall": 12.0,
         "steps_done": 120},
    ]
    spans, instants = spans_from_journal(events)
    hit = [s for s in spans if s["name"].startswith("cache hit")]
    assert len(hit) == 1
    assert hit[0]["cat"] == "cache"
    assert hit[0]["parent_span_id"] == submit_span_id("w")
    assert (hit[0]["t0"], hit[0]["t1"]) == (10.0, 10.01)
    assert hit[0]["args"]["donor"] == "d"
    assert hit[0]["trace_id"] == "t-1"
    pre = [i for i in instants if i["name"] == "cache_prefix"]
    assert len(pre) == 1 and pre[0]["args"]["generation_step"] == 60
    # the whole thing still exports as valid Chrome trace JSON
    doc = chrome_trace(spans, instants)
    assert any(e.get("name", "").startswith("cache hit")
               for e in doc["traceEvents"])


def test_fleet_counters_and_fail_on_gate(tmp_path):
    import importlib.util
    import sys as _sys

    spec = importlib.util.spec_from_file_location(
        "metrics_report", os.path.join(_ROOT, "tools",
                                       "metrics_report.py"))
    mr = importlib.util.module_from_spec(spec)
    _sys.modules.setdefault("metrics_report", mr)
    spec.loader.exec_module(mr)

    d, _ = _inline_daemon(tmp_path / "q")
    d.store.spool_submit(_spec("c1"))
    _run_until_terminal(d, "c1")
    d.store.spool_submit(_spec("c2"))
    _run_until_terminal(d, "c2")
    d.store.spool_submit(_spec("c3", steps=120))
    _run_until_terminal(d, "c3")
    d.close()
    doc = mr.summarize_fleet(str(tmp_path / "q"))
    f = doc["fleet"]
    assert f["cache_hits"] == 1
    assert f["cache_prefix_hits"] == 1
    assert f["cache_hit_rate"] == round(1 / 3, 4)
    assert f["cache_prefix_rate"] == round(1 / 3, 4)
    assert f["cache_bytes_saved"] > 0
    assert f["cache_steps_saved"] == 60 + 60
    # the shared --fail-on grammar gates the new counters: a floor
    # that holds, then one that doesn't
    exists, val = mr.resolve_metric(f, "cache_hit_rate")
    assert exists and val is not None
    assert "cache" in mr.render_fleet_text(doc)
    # Duplicate cache lines for ONE job (a daemon crash between the
    # cache line and its companion append replays the serve on
    # restart) must not inflate the distinct-job counters.
    store = JobStore(str(tmp_path / "q"), create=False)
    evs, _, _ = store.read_journal()
    dup = next(e for e in evs if e.get("event") == "cache_hit")
    store.journal.append("cache_hit", **{k: v for k, v in dup.items()
                                         if k not in ("schema",
                                                      "t_wall",
                                                      "pid",
                                                      "event")})
    store.close()
    f2 = mr.summarize_fleet(str(tmp_path / "q"))["fleet"]
    assert f2["cache_hits"] == 1
    assert f2["cache_steps_saved"] == f["cache_steps_saved"]
