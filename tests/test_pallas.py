"""Pallas kernels (interpreter mode on CPU) vs the jnp/XLA path.

Both paths use the identical f32 expression tree, but compile through
different pipelines (Mosaic / interpreter vs XLA fusion) whose FMA
contraction differs — so agreement is to a few ulp, not bitwise.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.ops import pallas_stencil as ps
from parallel_heat_tpu.ops.stencil import step_2d, step_2d_residual


def _close(got, want):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) * 10).astype(np.float32)


@pytest.mark.parametrize("k", [1, 2, 5, 20])
def test_vmem_multistep_matches_jnp(k):
    u = jnp.asarray(_rand((24, 36)))
    fn = ps._build_vmem_multistep((24, 36), "float32", 0.1, 0.1, k)
    got, res = fn(u)
    want = u
    for _ in range(k):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_vmem_multistep_bf16():
    u = jnp.asarray(_rand((16, 16))).astype(jnp.bfloat16)
    fn = ps._build_vmem_multistep((16, 16), "bfloat16", 0.1, 0.1, 4)
    got, _ = fn(u)
    want = u
    for _ in range(4):
        want = step_2d(want, 0.1, 0.1)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got.astype(jnp.float32)),
        np.asarray(want.astype(jnp.float32)),
        rtol=0.05, atol=0.05,
    )


@pytest.mark.parametrize("shape", [(64, 48), (96, 33), (40, 128)])
def test_strip_kernel_single_device_matches_jnp(shape):
    u = jnp.asarray(_rand(shape, seed=1))
    built = ps._build_strip_kernel(shape, "float32", 0.1, 0.1, shape,
                                   sharded=False)
    assert built is not None
    fn, _ = built
    got, res = fn(u, 0, 0)
    want, wres = step_2d_residual(u, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_strip_kernel_sharded_whole_grid_block():
    # A single block covering the whole grid: the halo slack rows are
    # garbage (zeros here) and block-edge columns coincide with the
    # global boundary, so the result must reproduce the full-grid step.
    bx, by = 32, 48
    u = jnp.asarray(_rand((bx, by), seed=2))
    built = ps._build_strip_kernel((bx, by), "float32", 0.1, 0.1,
                                   (bx, by), sharded=True)
    assert built is not None
    fn, sub = built
    u_ext = jnp.pad(u, ((sub, sub), (0, 0)))
    got, res = fn(u_ext, 0, 0)
    want, wres = step_2d_residual(u, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_strip_kernel_sharded_interior_block_with_halos():
    # Interior block of a larger global grid, halo rows delivered via
    # the slack rows: all rows update; block-edge columns are left to
    # the caller (unchanged here).
    full = jnp.asarray(_rand((64, 64), seed=3))
    bx, by = 16, 16
    r0, c0 = 16, 32  # block origin, interior
    block = full[r0:r0 + bx, c0:c0 + by]
    built = ps._build_strip_kernel((bx, by), "float32", 0.1, 0.1,
                                   (64, 64), sharded=True)
    fn, sub = built
    u_ext = jnp.pad(block, ((sub, sub), (0, 0)))
    u_ext = u_ext.at[sub - 1, :].set(full[r0 - 1, c0:c0 + by])
    u_ext = u_ext.at[sub + bx, :].set(full[r0 + bx, c0:c0 + by])
    got, _ = fn(u_ext, r0, c0)
    want = step_2d(full, 0.1, 0.1)[r0:r0 + bx, c0:c0 + by]
    _close(got[:, 1:-1], want[:, 1:-1])
    # edge columns are the caller's job: unchanged by the kernel
    np.testing.assert_array_equal(np.asarray(got[:, 0]),
                                  np.asarray(block[:, 0]))
    np.testing.assert_array_equal(np.asarray(got[:, -1]),
                                  np.asarray(block[:, -1]))
    # sanity: the interior actually changed
    assert not np.array_equal(np.asarray(got), np.asarray(block))


def test_solve_pallas_backend_matches_jnp_fixed():
    kw = dict(nx=48, ny=40, steps=23)
    a = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    b = solve(HeatConfig(backend="pallas", **kw)).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)


def test_solve_pallas_backend_matches_jnp_converge():
    kw = dict(nx=20, ny=20, steps=5000, converge=True, check_interval=20)
    a = solve(HeatConfig(backend="jnp", **kw))
    b = solve(HeatConfig(backend="pallas", **kw))
    assert a.converged == b.converged is True
    # ulp-level residual differences near the threshold may shift the
    # crossing by one check window at most
    assert abs(a.steps_run - b.steps_run) <= kw["check_interval"]
    np.testing.assert_allclose(a.to_numpy(), b.to_numpy(),
                               rtol=1e-3, atol=0.05)


def test_solve_pallas_sharded_matches_jnp():
    # halo_depth=1 pins the per-step block_steps path (the default None
    # auto-resolves to kernel G, covered by test_temporal).
    kw = dict(nx=32, ny=32, steps=11)
    a = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    b = solve(
        HeatConfig(backend="pallas", mesh_shape=(2, 2), halo_depth=1, **kw)
    ).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)


def test_pick_strip_rows():
    # divides out_rows, multiple of the sublane tile, VMEM-bounded
    t = ps._pick_strip_rows(4096, 4096, "float32", sharded=False)
    assert t is not None and 4096 % t == 0 and t % 8 == 0
    assert ps._pick_strip_rows(16384, 16384, "float32", sharded=False) \
        is not None
    # 32768-wide bf16 rows: the f32 cast temporaries cap the strip
    # height at a skinny 64 rows — the solver prefers the 2D-tiled
    # kernel there (better window efficiency).
    t32 = ps._pick_strip_rows(32768, 32768, "bfloat16", sharded=False)
    assert t32 is not None and t32 % 16 == 0
    tc = ps._pick_tile_2d(32768, 32768, "bfloat16", sharded=False)
    eff_b = t32 / (t32 + 32)
    eff_c = tc[0] * tc[1] / ((tc[0] + 32) * (tc[1] + 256))
    assert eff_c > eff_b
    t16 = ps._pick_strip_rows(16384, 16384, "bfloat16", sharded=False)
    assert t16 is not None and t16 % 16 == 0
    # odd geometry declines
    assert ps._pick_strip_rows(1000, 33, "float32", sharded=False) == 200
    assert ps._pick_strip_rows(7, 64, "float32", sharded=False) is None


def test_fits_vmem():
    assert ps.fits_vmem((1000, 1000), "float32")
    assert ps.fits_vmem((1024, 1024), "float32")
    assert not ps.fits_vmem((4096, 4096), "float32")
    assert ps.fits_vmem((2048, 1024), "bfloat16")


def test_solve_pallas_sharded_single_column_blocks():
    # mesh (1,8) on ny=8 -> by=1 blocks: the strip kernel must decline
    # and the jnp halo fallback must keep results identical.
    kw = dict(nx=64, ny=8, steps=5)
    a = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    b = solve(
        HeatConfig(backend="pallas", mesh_shape=(1, 8), **kw)
    ).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)


def test_tiled_kernel_single_device_matches_jnp():
    # Wide grid forcing >= 2 column chunks (CW=1024).
    shape = (32, 2048)
    u = jnp.asarray(_rand(shape, seed=5))
    built = ps._build_tiled_kernel(shape, "float32", 0.1, 0.1, shape,
                                   sharded=False)
    assert built is not None
    fn, _ = built
    got, res = fn(u, 0, 0)
    want, wres = step_2d_residual(u, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_tiled_kernel_sharded_interior_block():
    # Sharded mode: halo rows via slack rows, edge columns left alone.
    O, N = 16, 2048
    full = jnp.asarray(_rand((O + 2, N), seed=6))
    block = full[1:-1, :]
    built = ps._build_tiled_kernel((O, N), "float32", 0.1, 0.1,
                                   (1000, 4096), sharded=True)
    assert built is not None
    fn, sub = built
    u_ext = jnp.pad(block, ((sub, sub), (0, 0)))
    u_ext = u_ext.at[sub - 1, :].set(full[0, :])
    u_ext = u_ext.at[sub + O, :].set(full[-1, :])
    r0, c0 = 100, 1024  # interior of the (1000, 4096) global grid
    got, _ = fn(u_ext, r0, c0)
    want = step_2d(full, 0.1, 0.1)[1:-1, :]
    _close(got[:, 1:-1], want[:, 1:-1])
    np.testing.assert_array_equal(np.asarray(got[:, 0]),
                                  np.asarray(block[:, 0]))


def test_pick_tile_2d():
    t = ps._pick_tile_2d(32768, 32768, "bfloat16", sharded=False)
    assert t is not None
    T, CW = t
    assert 32768 % T == 0 and T % 16 == 0
    assert 32768 % CW == 0 and CW % 128 == 0
    # narrow grids decline (kernel B's territory)
    assert ps._pick_tile_2d(1000, 1000, "float32", sharded=False) is None


def test_slab_kernel_3d_matches_jnp():
    from parallel_heat_tpu.ops.stencil import step_3d_residual

    shape = (16, 48, 128)
    rng = np.random.default_rng(7)
    u = jnp.asarray((rng.standard_normal(shape) * 10).astype(np.float32))
    fn = ps._build_slab_kernel_3d(shape, "float32", 0.1, 0.1, 0.1)
    assert fn is not None
    got, res = fn(u)
    want, wres = step_3d_residual(u, 0.1, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_solve_pallas_3d_matches_jnp():
    kw = dict(nx=16, ny=16, nz=128, steps=7)
    a = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
    b = solve(HeatConfig(backend="pallas", **kw)).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)


# --------------------------------------------------------------------------
# Kernel F: 3D X-slab streaming, temporal-blocked
# --------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 2, 4])
def test_xslab_3d_matches_jnp(k):
    from parallel_heat_tpu.ops.stencil import step_3d_residual

    shape = (24, 16, 128)
    rng = np.random.default_rng(8)
    u = jnp.asarray((rng.standard_normal(shape) * 10).astype(np.float32))
    fn = ps._build_xslab_3d(shape, "float32", 0.1, 0.1, 0.1, 8, k)
    got, res = fn(u)
    want = u
    for _ in range(k):
        want, wres = step_3d_residual(want, 0.1, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_xslab_multistep_3d_chunks():
    # Full K-sized passes plus a remainder pass; residual = last step's.
    from parallel_heat_tpu.ops.stencil import step_3d_residual

    shape = (24, 16, 128)
    rng = np.random.default_rng(9)
    u = jnp.asarray((rng.standard_normal(shape) * 10).astype(np.float32))
    built = ps._xslab_multistep_3d(shape, "float32", 0.1, 0.1, 0.1)
    assert built is not None
    multi_step, multi_step_residual = built
    got, res = multi_step_residual(u, 10)
    want = u
    for _ in range(10):
        want, wres = step_3d_residual(want, 0.1, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(multi_step(u, 10)),
                                  np.asarray(got))


def test_xslab_3d_dirichlet_boundary():
    # All six faces bit-identical to the input after K steps.
    shape = (16, 16, 128)
    rng = np.random.default_rng(10)
    u = jnp.asarray((rng.standard_normal(shape) * 10).astype(np.float32))
    fn = ps._build_xslab_3d(shape, "float32", 0.1, 0.1, 0.1, 8, 3)
    got, _ = fn(u)
    g, w = np.asarray(got), np.asarray(u)
    np.testing.assert_array_equal(g[0], w[0])
    np.testing.assert_array_equal(g[-1], w[-1])
    np.testing.assert_array_equal(g[:, 0, :], w[:, 0, :])
    np.testing.assert_array_equal(g[:, -1, :], w[:, -1, :])
    np.testing.assert_array_equal(g[:, :, 0], w[:, :, 0])
    np.testing.assert_array_equal(g[:, :, -1], w[:, :, -1])


def test_pick_xslab_3d():
    # Unaligned Z declines; aligned Z returns a geometry that divides X.
    assert ps._pick_xslab_3d((64, 64, 100), "float32") is None
    pick = ps._pick_xslab_3d((512, 512, 512), "float32")
    assert pick is not None
    sx, k = pick
    assert 512 % sx == 0 and 1 <= k <= 8


def test_solve_sharded_tiled_kernel_end_to_end(monkeypatch):
    # Force block_steps down the strip-declines -> tiled-accepts branch
    # (normally reached only on very wide shard blocks) and check the
    # full shard_map integration: vma annotations, SUB pre/post padding,
    # halo rows, edge-column epilogue.
    from parallel_heat_tpu import solver as slv

    monkeypatch.setattr(ps, "_build_strip_kernel",
                        lambda *a, **k: None)
    slv._build_runner.cache_clear()
    kw = dict(nx=32, ny=4096, steps=5)
    try:
        a = solve(HeatConfig(backend="jnp", **kw)).to_numpy()
        b = solve(
            HeatConfig(backend="pallas", mesh_shape=(2, 2), halo_depth=1,
                       **kw)
        ).to_numpy()
    finally:
        slv._build_runner.cache_clear()  # drop runners built on the mock
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)


# --------------------------------------------------------------------------
# Kernel E: temporally-blocked streaming strip
# --------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 3, 8])
def test_temporal_strip_matches_jnp(k):
    shape = (64, 128)
    u = jnp.asarray(_rand(shape, seed=3))
    fn = ps._build_temporal_strip(shape, "float32", 0.1, 0.1, k)
    assert fn is not None
    got, res = fn(u)
    want = u
    for _ in range(k):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)


def test_temporal_multistep_chunks():
    # 20 steps = K-sized passes plus a remainder pass; the residual must
    # be the last step's, exactly as the jnp chain computes it.
    shape = (64, 128)
    u = jnp.asarray(_rand(shape, seed=4))
    built = ps._temporal_multistep(shape, "float32", 0.1, 0.1)
    assert built is not None
    multi_step, multi_step_residual = built
    got, res = multi_step_residual(u, 20)
    want = u
    for _ in range(20):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4, atol=1e-6)
    got2 = multi_step(u, 20)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(got2))


def test_temporal_strip_dirichlet_boundary():
    # Boundary cells must be bit-identical to the input after K steps.
    shape = (64, 128)
    u = jnp.asarray(_rand(shape, seed=5))
    fn = ps._build_temporal_strip(shape, "float32", 0.1, 0.1, 8)
    got, _ = fn(u)
    g, w = np.asarray(got), np.asarray(u)
    np.testing.assert_array_equal(g[0, :], w[0, :])
    np.testing.assert_array_equal(g[-1, :], w[-1, :])
    np.testing.assert_array_equal(g[:, 0], w[:, 0])
    np.testing.assert_array_equal(g[:, -1], w[:, -1])


def test_temporal_strip_bf16_matches_jnp():
    # Sub-f32 storage: f32 arithmetic with per-step rounding to bf16 in
    # VMEM scratch — must agree with K jnp steps (which round to bf16 in
    # HBM each step) up to FMA-contraction differences.
    shape = (96, 128)
    k = 6
    u = jnp.asarray(_rand(shape, seed=6)).astype(jnp.bfloat16)
    fn = ps._build_temporal_strip(shape, "bfloat16", 0.1, 0.1, k)
    assert fn is not None
    got, res = fn(u)
    assert got.dtype == jnp.bfloat16
    want = u
    for _ in range(k):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    np.testing.assert_allclose(
        np.asarray(got.astype(jnp.float32)),
        np.asarray(want.astype(jnp.float32)),
        rtol=0.05, atol=0.05,
    )
    np.testing.assert_allclose(float(res), float(wres), rtol=0.1, atol=1e-4)
    # Dirichlet boundary bit-exact through the cast round trips.
    g, w = np.asarray(got), np.asarray(u)
    np.testing.assert_array_equal(g[0, :], w[0, :])
    np.testing.assert_array_equal(g[:, -1], w[:, -1])


def test_temporal_pick_declines_small_rows():
    # Too few rows for a clamped window (O < 3*SUB): decline.
    assert ps._pick_temporal_strip(16, 128, "float32") is None


def test_temporal_block_kernel_single_block_vs_jnp():
    # Kernel G driven directly (one block covering the whole grid,
    # zero-padded K-deep halo + lane-alignment junk columns) — the same
    # construction validated on real TPU hardware (Mosaic-compiled;
    # Mosaic requires the lane-aligned width this test exercises).
    from parallel_heat_tpu.models import HeatPlate2D

    K = 8
    for bx, by in [(16, 24), (32, 112)]:  # 24+16=40 -> pad; 112+16=128 -> none
        m = HeatPlate2D(bx, by)
        u0 = m.init_grid(jnp.float32)
        fn = ps._build_temporal_block((bx, by), "float32", 0.1, 0.1,
                                      (bx, by), K)
        assert fn is not None
        pad = fn.padded_width - (by + 2 * K)
        ext = jnp.pad(u0, ((K, K), (K, K + pad)))
        core_rows, res = fn(ext, 0, -K)
        got = np.asarray(core_rows)[:, K:K + by]
        want = u0
        for _ in range(K):
            want = step_2d(want, 0.1, 0.1)
        np.testing.assert_allclose(got, np.asarray(want),
                                   rtol=1e-5, atol=1e-3)
        assert float(res) > 0


# --------------------------------------------------------------------------
# Kernel I: 2D-tiled temporal (wide grids)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 3, 8])
def test_tile_temporal_matches_jnp(k):
    from parallel_heat_tpu.models import HeatPlate2D
    from parallel_heat_tpu.ops.stencil import step_2d

    M, N = 32, 64  # interpret-mode tile candidates admit small CW
    fn = ps._build_tile_temporal_2d((M, N), "float32", 0.1, 0.1, k)
    assert fn is not None
    u = HeatPlate2D(M, N).init_grid(jnp.float32)
    got, res = fn(u)
    want = u
    for _ in range(k):
        want = step_2d(want, 0.1, 0.1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)
    assert float(res) >= 0.0


def test_tile_temporal_diverging_boundary_exact():
    from parallel_heat_tpu.models import HeatPlate2D

    M, N = 32, 64
    fn = ps._build_tile_temporal_2d((M, N), "float32", 0.9, 0.9, 8)
    u0 = HeatPlate2D(M, N).init_grid(jnp.float32)
    u = u0
    for _ in range(10):
        u, _ = fn(u)
    out = np.asarray(u)
    assert not np.all(np.isfinite(out))
    ini = np.asarray(u0)
    for sl in [np.s_[0], np.s_[-1], np.s_[:, 0], np.s_[:, -1]]:
        np.testing.assert_array_equal(out[sl], ini[sl])


def test_pick_single_2d_prefers_I_for_wide_bf16(monkeypatch):
    # The measured rule: sub-f32 grids where kernel I's window
    # amplification beats kernel E's route to the I family (32768^2
    # bf16 on v5e: 166.3 vs 153.7 Gcells*steps/s); f32 always keeps
    # the E family where E builds (measured 16384^2: E 208.7 vs I
    # 142.8). Within each family, the wide-row cost model then picks
    # the uniform-gather schedule exactly past the measured knee
    # (these geometries all sweep > 8448 lanes, so they route to the
    # -uni variants; below-knee picks stay windowed — see
    # test_uniform_pick_is_cost_model_driven). Pinned under HARDWARE
    # alignment rules (the production decision), not the
    # interpret-mode parameters this suite otherwise runs with — the
    # pick functions never build kernels, so forcing the flag is safe.
    monkeypatch.setattr(ps, "_needs_lane_alignment", lambda: True)
    kind, ti = ps.pick_single_2d((32768, 32768), "bfloat16", 0.1, 0.1)
    assert kind == "I-uni" and ti == (256, 8192)
    kind, _ = ps.pick_single_2d((16384, 16384), "float32", 0.1, 0.1)
    assert kind == "E-uni"
    kind, _ = ps.pick_single_2d((16384, 16384), "bfloat16", 0.1, 0.1)
    assert kind == "E-uni"


# --------------------------------------------------------------------------
# Kernels E-uni / I-uni: uniform-window gather variants
# --------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 3, 8])
def test_temporal_strip_uniform_bitwise_vs_e(k):
    # The uniform gather moves the same bytes to the same scratch rows
    # (core + conditional edge halos instead of one re-shaping
    # window), so E-uni must be BITWISE kernel E — and therefore match
    # the jnp oracle to E's own contract.
    shape = (64, 128)
    u = jnp.asarray(_rand(shape, seed=3))
    fe = ps._build_temporal_strip(shape, "float32", 0.1, 0.1, k)
    fu = ps._build_temporal_strip_uniform(shape, "float32", 0.1, 0.1, k)
    assert fu is not None
    ge, re_ = fe(u)
    gu, ru = fu(u)
    np.testing.assert_array_equal(np.asarray(ge), np.asarray(gu))
    assert float(re_) == float(ru)
    want = u
    for _ in range(k):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    _close(gu, want)
    np.testing.assert_allclose(float(ru), float(wres), rtol=1e-4,
                               atol=1e-6)


def test_temporal_strip_uniform_bf16_and_plain():
    # bf16 (SUB=16 halos) and the no-residual builder the converge
    # path's non-final calls use — both bitwise kernel E's twins.
    shape = (96, 128)
    u = jnp.asarray(_rand(shape, seed=6)).astype(jnp.bfloat16)
    for res in (True, False):
        fe = ps._build_temporal_strip(shape, "bfloat16", 0.1, 0.1, 16,
                                      with_residual=res)
        fu = ps._build_temporal_strip_uniform(shape, "bfloat16",
                                              0.1, 0.1, 16,
                                              with_residual=res)
        assert fu is not None
        ge, re_ = fe(u)
        gu, ru = fu(u)
        np.testing.assert_array_equal(np.asarray(ge), np.asarray(gu))
        assert float(re_) == float(ru)


def test_temporal_uniform_multistep_fixed_and_converge():
    # The lifted multistep (full chunks + remainder + last-step fused
    # residual — the fixed AND converge entry points) stays bitwise
    # the windowed lifting's.
    shape = (64, 128)
    u = jnp.asarray(_rand(shape, seed=4))
    mw = ps._temporal_multistep(shape, "float32", 0.1, 0.1)
    mu = ps._temporal_multistep(shape, "float32", 0.1, 0.1,
                                uniform=True)
    for n in (20, 8, 3):
        gw = mw[0](u, n)
        gu = mu[0](u, n)
        np.testing.assert_array_equal(np.asarray(gw), np.asarray(gu))
        gw, rw = mw[1](u, n)
        gu, ru = mu[1](u, n)
        np.testing.assert_array_equal(np.asarray(gw), np.asarray(gu))
        assert float(rw) == float(ru)


@pytest.mark.parametrize("case", [((32, 64), "float32", 8),
                                  ((32, 64), "float32", 3),
                                  ((64, 256), "bfloat16", 16)])
def test_tile_temporal_uniform_bitwise_vs_i(case):
    shape, dt, k = case
    u = jnp.asarray(_rand(shape, seed=7)).astype(jnp.dtype(dt))
    fi = ps._build_tile_temporal_2d(shape, dt, 0.1, 0.1, k)
    fu = ps._build_tile_temporal_2d_uniform(shape, dt, 0.1, 0.1, k)
    assert fi is not None and fu is not None
    gi, ri = fi(u)
    gu, ru = fu(u)
    np.testing.assert_array_equal(np.asarray(gi), np.asarray(gu))
    assert float(ri) == float(ru)
    # plain builder too
    fip = ps._build_tile_temporal_2d(shape, dt, 0.1, 0.1, k,
                                     with_residual=False)
    fup = ps._build_tile_temporal_2d_uniform(shape, dt, 0.1, 0.1, k,
                                             with_residual=False)
    np.testing.assert_array_equal(np.asarray(fip(u)[0]),
                                  np.asarray(fup(u)[0]))


def test_temporal_uniform_acc_f32_bitwise():
    # f32chunk accumulation: the uniform variants share kernel E/I's
    # f32 ping-pong discipline — bitwise twins in acc mode too.
    shape = (96, 128)
    u = jnp.asarray(_rand(shape, seed=8)).astype(jnp.bfloat16)
    fe = ps._build_temporal_strip(shape, "bfloat16", 0.1, 0.1, 16,
                                  acc_f32=True)
    fu = ps._build_temporal_strip_uniform(shape, "bfloat16", 0.1, 0.1,
                                          16, acc_f32=True)
    np.testing.assert_array_equal(np.asarray(fe(u)[0]),
                                  np.asarray(fu(u)[0]))
    shape = (64, 256)
    u = jnp.asarray(_rand(shape, seed=9)).astype(jnp.bfloat16)
    fi = ps._build_tile_temporal_2d(shape, "bfloat16", 0.1, 0.1, 16,
                                    acc_f32=True)
    fiu = ps._build_tile_temporal_2d_uniform(shape, "bfloat16",
                                             0.1, 0.1, 16,
                                             acc_f32=True)
    np.testing.assert_array_equal(np.asarray(fi(u)[0]),
                                  np.asarray(fiu(u)[0]))


def test_temporal_strip_uniform_diverging_boundary_exact():
    shape = (64, 128)
    u0 = jnp.asarray(_rand(shape, seed=5))
    fu = ps._build_temporal_strip_uniform(shape, "float32", 0.9, 0.9, 8)
    u = u0
    for _ in range(20):
        u, _ = fu(u)
    out = np.asarray(u)
    assert not np.all(np.isfinite(out))
    ini = np.asarray(u0)
    for sl in [np.s_[0], np.s_[-1], np.s_[:, 0], np.s_[:, -1]]:
        np.testing.assert_array_equal(out[sl], ini[sl])


def test_uniform_pick_is_cost_model_driven(monkeypatch):
    # The windowed-vs-uniform choice comes from the measured wide-row
    # cost model, never a hard-coded override: below the knee (8448
    # swept lanes) the modeled scores tie and the incumbent windowed
    # kernels keep the pick; past it the uniform schedule's shallower
    # measured slope wins strictly. Hardware alignment rules, pick
    # functions only (no kernel builds).
    monkeypatch.setattr(ps, "_needs_lane_alignment", lambda: True)
    assert ps.pick_single_2d((8192, 8192), "float32", 0.1, 0.1)[0] == "E"
    assert ps.pick_single_2d((4096, 4096), "float32", 0.1, 0.1)[0] == "E"
    assert ps.pick_single_2d((16384, 16384), "float32",
                             0.1, 0.1)[0] == "E-uni"
    # f32chunk branch runs the same comparison
    assert ps.pick_single_2d((16384, 16384), "bfloat16", 0.1, 0.1,
                             accumulate="f32chunk")[0] == "E-uni"
    assert ps.pick_single_2d((32768, 32768), "bfloat16", 0.1, 0.1,
                             accumulate="f32chunk")[0] == "I-uni"
    # the model parameters themselves drive the choice: with the
    # uniform slope pinned equal to the windowed one the advantage
    # vanishes and the pick reverts — no override anywhere
    from parallel_heat_tpu.ops import tpu_params as tpp

    base = tpp.params()
    try:
        tpp.set_override(tpp.TpuParams(
            base.kind, base.vmem_bytes, base.hbm_stream_bytes_per_s,
            base.vpu_cells_per_s,
            wide_row_slope_uniform_per_16k=base.wide_row_slope_per_16k))
        assert ps.pick_single_2d((16384, 16384), "float32",
                                 0.1, 0.1)[0] == "E"
    finally:
        tpp.set_override(None)


def test_uniform_decline_paths(monkeypatch):
    # Each decline path falls back to the windowed kernel, never jnp:
    # (1) 2-strip geometries — the uniform picker caps T at rows//3,
    #     so short grids decline at pick time;
    assert ps._pick_temporal_strip(16, 128, "float32",
                                   uniform=True) is None
    # (2) the builder's own n_strips >= 3 backstop (reachable only if
    #     the picker drifts — forced here);
    monkeypatch.setattr(ps, "_pick_temporal_strip",
                        lambda *a, **k: 32)
    ps._build_temporal_strip_uniform.cache_clear()
    assert ps._build_temporal_strip_uniform((64, 128), "float32",
                                            0.1, 0.1, 8) is None
    ps._build_temporal_strip_uniform.cache_clear()
    monkeypatch.undo()
    # (3) lane-misaligned widths on hardware decline the whole
    #     temporal family; the pick must not be a uniform kind;
    monkeypatch.setattr(ps, "_needs_lane_alignment", lambda: True)
    kind, _ = ps.pick_single_2d((16384, 16400), "float32", 0.1, 0.1)
    assert kind not in ("E-uni", "I-uni")
    monkeypatch.undo()
    # (4) a uniform builder decline inside the multistep factory falls
    #     back to the windowed kernel E (not None, not a crash).
    monkeypatch.setattr(ps, "_build_temporal_strip_uniform",
                        lambda *a, **k: None)
    mu = ps._temporal_multistep((64, 128), "float32", 0.1, 0.1,
                                uniform=True)
    assert mu is not None
    u = jnp.asarray(_rand((64, 128), seed=2))
    mw = ps._temporal_multistep((64, 128), "float32", 0.1, 0.1)
    np.testing.assert_array_equal(np.asarray(mu[0](u, 12)),
                                  np.asarray(mw[0](u, 12)))


def test_uniform_dispatch_end_to_end(monkeypatch):
    # single_grid_multistep must route the uniform kinds to the
    # uniform factories (forced pick: interpret-mode sizes never sit
    # past the wide-row knee) and produce the jnp chain's results.
    from parallel_heat_tpu.config import HeatConfig

    shape = (64, 128)
    monkeypatch.setattr(ps, "pick_single_2d",
                        lambda *a, **k: ("E-uni", 16))
    cfg = HeatConfig(nx=shape[0], ny=shape[1], backend="pallas")
    ms, msr = ps.single_grid_multistep(cfg)
    u = jnp.asarray(_rand(shape, seed=11))
    got, res = msr(u, 20)
    want = u
    for _ in range(20):
        want, wres = step_2d_residual(want, 0.1, 0.1)
    _close(got, want)
    np.testing.assert_allclose(float(res), float(wres), rtol=1e-4,
                               atol=1e-6)
