"""Error paths of utils/profiling.py: the refusals that keep the
timing tools from printing garbage rates, and the Timeline's
empty-summary behavior (ISSUE 3 satellites)."""

import pytest

from parallel_heat_tpu.utils import measure
from parallel_heat_tpu.utils import profiling as prof


def test_chain_slope_raises_on_non_positive_slope(monkeypatch):
    # Flat endpoints (all dispatch floor, no per-call signal): the
    # slope is zero and chain_slope must refuse, not divide it out.
    # (The protocol lives in utils/measure.py now — profiling
    # re-exports it — so the stub targets the measure module.)
    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.2)
    with pytest.raises(RuntimeError, match="non-positive chained slope"):
        prof.chain_slope(None, None, 1, 33)
    # Inverted endpoints (noise swamped the long batch): same refusal.
    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.2 - 1e-4 * reps)
    with pytest.raises(RuntimeError, match="measurement noise"):
        prof.chain_slope(None, None, 1, 33, batches=2)


def test_chain_slope_happy_path(monkeypatch):
    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.2 + 2e-3 * reps)
    assert prof.chain_slope(None, None, 1, 101) == pytest.approx(2e-3)


def test_calibrated_slope_short_span_refusal(monkeypatch):
    # max_reps cannot hold 60% of span_s of device work: refuse with
    # the actionable message rather than report a noise-dominated rate.
    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.2 + 1e-3 * reps)
    with pytest.raises(RuntimeError, match="raise max_reps"):
        prof.calibrated_slope(None, None, span_s=10.0, max_reps=100)


def test_step_stats_bytes_per_cell_tracks_dtype():
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.solver import HeatResult

    res = HeatResult(grid=None, steps_run=4, converged=None,
                     residual=None, elapsed_s=0.5)
    for dtype, expect in (("float32", 8), ("bfloat16", 4),
                          ("float64", 16)):
        cfg = HeatConfig(nx=32, ny=32, steps=4, dtype=dtype,
                         backend="jnp")
        st = prof.step_stats(res, cfg)
        assert st.bytes_per_cell == expect
        assert st.effective_hbm_gb_s == pytest.approx(
            1024 * expect * 4 / 0.5 / 1e9)
    # f32chunk shares the storage-dtype traffic model (the f32 carry
    # lives in VMEM, not HBM)
    cfg = HeatConfig(nx=16, ny=128, steps=4, dtype="bfloat16",
                     accumulate="f32chunk", backend="jnp")
    assert prof.step_stats(res, cfg).bytes_per_cell == 4


def test_timeline_empty_summary_is_friendly():
    tl = prof.Timeline()
    s = tl.summary()  # no phases marked: no ZeroDivisionError
    assert "no phases" in s


def test_timeline_zero_total_summary():
    tl = prof.Timeline()
    tl.phases = [("a", 0.0), ("b", 0.0)]  # sub-resolution phases
    s = tl.summary()
    assert "a" in s and "total" in s and "nan" not in s


def test_timeline_normal_summary_unchanged():
    tl = prof.Timeline()
    tl.phases = [("init", 1.0), ("run", 3.0)]
    s = tl.summary()
    assert "( 25.0%)" in s and "( 75.0%)" in s
    assert "4.0000s" in s
