"""Chaos suite: the run supervisor under injected faults.

Every test runs on CPU (the 8-virtual-device conftest) — the failure
machinery under test is host-side orchestration around the same
compiled programs every backend uses, so CPU coverage IS the coverage
(`pytest -m chaos` is the Makefile smoke line). The acceptance
contract, from ISSUE 2:

- NaN injected at step k in FIXED-STEP mode (where the reference and
  the pre-supervisor repo checked nothing) is detected within one
  ``guard_interval``;
- a transient fault rolls back and recovers, bitwise equal to the
  uninterrupted run; a permanent fault (stability violation, or a
  fault that survives the retry budget) halts with a diagnosis;
- SIGTERM mid-run leaves a loadable checkpoint whose resumed run
  matches the uninterrupted run bitwise;
- with the guard/supervisor disabled, ``solve`` outputs are bitwise
  unchanged.
"""

import os
import signal
import sys
import warnings

import numpy as np
import pytest

from parallel_heat_tpu import (
    EXIT_PERMANENT_FAILURE,
    EXIT_PREEMPTED,
    HeatConfig,
    PermanentFailure,
    SupervisorPolicy,
    Telemetry,
    run_supervised,
    solve,
    solve_stream,
)
from parallel_heat_tpu.utils.checkpoint import (
    generation_paths,
    latest_checkpoint,
    load_checkpoint,
)
from parallel_heat_tpu.utils.faults import FaultPlan, InjectedTransientError

pytestmark = pytest.mark.chaos

_BASE = dict(nx=16, ny=16, backend="jnp")


def _policy(**kw):
    kw.setdefault("checkpoint_every", 20)
    kw.setdefault("guard_interval", 10)
    kw.setdefault("backoff_base_s", 0.0)  # no real sleeping in tests
    return SupervisorPolicy(**kw)


# ---------------------------------------------------------------------------
# The guard alone (no supervisor)
# ---------------------------------------------------------------------------

def test_guard_disabled_is_bitwise_identical_and_silent():
    clean = solve(HeatConfig(steps=60, **_BASE))
    assert clean.finite is None  # no guard -> no verdict
    guarded = solve(HeatConfig(steps=60, guard_interval=10, **_BASE))
    np.testing.assert_array_equal(guarded.to_numpy(), clean.to_numpy())
    assert guarded.finite is True


def test_guard_detects_blowup_in_fixed_step_stream():
    # Unstable coefficients in FIXED-STEP mode: before the guard,
    # nothing in the repo checked this (converge mode at least saw its
    # residual go NaN). The guard must flag it within one interval.
    cfg = HeatConfig(steps=100, cx=5.0, cy=5.0, guard_interval=10,
                     **_BASE)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        flags = [(r.steps_run, r.finite)
                 for r in solve_stream(cfg, chunk_steps=10)]
    # every chunk boundary is a guard boundary here: no None verdicts
    assert all(f is not None for _, f in flags)
    first_bad = next(s for s, f in flags if f is False)
    # 16x16 f32 with cx=cy=5 overflows within a few dozen steps; once
    # bad, it stays bad — and the warning fired.
    assert first_bad <= 60
    assert all(not f for s, f in flags if s >= first_bad)
    assert any("runtime guard" in str(x.message) for x in w)


def test_guard_interval_cadence_leaves_between_chunks_unchecked():
    cfg = HeatConfig(steps=60, guard_interval=20, **_BASE)
    flags = [(r.steps_run, r.finite)
             for r in solve_stream(cfg, chunk_steps=10)]
    assert flags == [(10, None), (20, True), (30, None), (40, True),
                     (50, None), (60, True)]


def test_guard_checks_final_chunk_even_off_boundary():
    # steps < guard_interval: the end state must still be checked (a
    # short stream is not a license to skip guarding — solve() checks
    # its end state too).
    cfg = HeatConfig(steps=50, cx=5.0, cy=5.0, guard_interval=60,
                     **_BASE)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        flags = [(r.steps_run, r.finite)
                 for r in solve_stream(cfg, chunk_steps=25)]
    assert flags[-1][0] == 50 and flags[-1][1] is False
    assert flags[:-1] == [(25, None)]
    assert any("runtime guard" in str(x.message) for x in w)


def test_supervisor_warns_on_non_nested_cadences(tmp_path):
    with pytest.warns(RuntimeWarning, match="dispatch chunk is gcd"):
        run_supervised(HeatConfig(steps=30, **_BASE), tmp_path / "ck",
                       policy=_policy(checkpoint_every=15,
                                      guard_interval=10))


def test_guard_off_in_stream_yields_none_verdicts():
    flags = [r.finite for r in
             solve_stream(HeatConfig(steps=30, **_BASE), chunk_steps=10)]
    assert flags == [None, None, None]


# ---------------------------------------------------------------------------
# Supervisor: recovery, halts, preemption
# ---------------------------------------------------------------------------

def test_supervisor_clean_run_matches_solve_bitwise(tmp_path):
    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(HeatConfig(steps=60, **_BASE),
                          tmp_path / "ck", policy=_policy())
    assert not sres.interrupted and sres.retries == 0
    assert sres.steps_done == 60
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())
    # generation zero + the periodic saves, pruned to keep_checkpoints
    steps = [s for s, _ in generation_paths(tmp_path / "ck")]
    assert steps == [20, 40, 60]


def test_supervisor_detects_nan_within_one_guard_interval(tmp_path):
    k = 35
    sres = run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                          policy=_policy(),
                          faults=FaultPlan(nan_at_step=k))
    assert sres.guard_trips == 1
    (detected,) = sres.guard_trip_steps
    assert 0 < detected - k <= 10  # within one guard_interval of k


def test_supervisor_recovers_transient_nan_bitwise(tmp_path):
    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                          policy=_policy(),
                          faults=FaultPlan(nan_at_step=35))
    assert sres.retries == 1 and sres.rollbacks == 1
    assert sres.steps_done == 60
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_one_shot_nan_on_unguarded_boundary_still_detected(tmp_path):
    # chunk = gcd(15, 10) = 5: an injection at step 3 would land on
    # boundary 5, which neither the guard nor the checkpoint schedule
    # inspects — the plan defers it to the first GUARDED boundary (10)
    # instead of letting the one-shot fault be silently consumed (and
    # the cell certify a detection that never ran).
    clean = solve(HeatConfig(steps=60, **_BASE))
    with pytest.warns(RuntimeWarning, match="dispatch chunk"):
        sres = run_supervised(
            HeatConfig(steps=60, **_BASE), tmp_path / "ck",
            policy=_policy(checkpoint_every=15, guard_interval=10),
            faults=FaultPlan(nan_at_step=3))
    assert sres.guard_trips == 1
    assert sres.guard_trip_steps[0] == 10
    assert sres.rollbacks == 1
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_supervisor_recovers_transient_dispatch_error_bitwise(tmp_path):
    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                          policy=_policy(),
                          faults=FaultPlan(transient_on_chunks=(2,)))
    assert sres.retries == 1 and sres.guard_trips == 0
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_supervisor_halts_permanent_on_stability_violation(tmp_path):
    cfg = HeatConfig(steps=100, cx=5.0, cy=5.0, **_BASE)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(PermanentFailure) as ei:
            run_supervised(cfg, tmp_path / "ck", policy=_policy())
    msg = str(ei.value)
    # the diagnosis is actionable: names the bound, the margin, the
    # first bad chunk window, and the no-retry verdict
    assert "stability bound" in msg and "margin" in msg
    assert "steps (" in msg and "retrying cannot help" in msg
    # ...and the escape hatch: the implicit integrator takes steps of
    # any size (SEMANTICS.md "Implicit stepping"; regression-pinned
    # alongside config.validate()'s warning string)
    assert "--scheme backward_euler" in msg
    # no retries were burned on a deterministic blow-up
    assert "rollback retr" not in msg
    assert ei.value.kind == "unstable"


def test_supervisor_implicit_scheme_not_classified_unstable(tmp_path):
    # The same coefficients under backward_euler are NOT a stability
    # violation: the implicit run completes supervised, no trips.
    cfg = HeatConfig(steps=100, cx=5.0, cy=5.0,
                     scheme="backward_euler", **_BASE)
    sres = run_supervised(cfg, tmp_path / "ck", policy=_policy())
    assert sres.result.steps_run == 100
    assert sres.guard_trips == 0 and sres.retries == 0


def test_supervisor_exhausts_retry_budget_on_recurring_fault(tmp_path):
    with pytest.raises(PermanentFailure) as ei:
        run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                       policy=_policy(max_retries=2),
                       faults=FaultPlan(nan_at_step=35, recurring=True))
    msg = str(ei.value)
    assert "2 rollback retries" in msg
    assert "First bad chunk" in msg
    # the newest checkpoint named in the diagnosis is loadable and good
    p = latest_checkpoint(tmp_path / "ck")
    assert p is not None and str(p) in msg
    grid, step, _ = load_checkpoint(p)
    assert np.isfinite(np.asarray(grid, dtype=np.float64)).all()
    assert step < 35


def test_supervisor_unknown_errors_are_not_retried(tmp_path):
    # A deterministic bug (here: a TypeError from a hostile fault hook)
    # must propagate, not be classified transient and retried.
    class Hostile:
        def before_chunk(self):
            raise TypeError("not a fault the classifier knows")

        def corrupt(self, grid, step):
            return grid

    with pytest.raises(TypeError):
        run_supervised(HeatConfig(steps=40, **_BASE), tmp_path / "ck",
                       policy=_policy(), faults=Hostile())


def test_sigterm_mid_run_checkpoint_then_resume_bitwise(tmp_path):
    clean = solve(HeatConfig(steps=100, **_BASE))
    stem = tmp_path / "ck"
    sres = run_supervised(HeatConfig(steps=100, **_BASE), stem,
                          policy=_policy(),
                          faults=FaultPlan(signal_at_chunk=3,
                                           signum=int(signal.SIGTERM)))
    assert sres.interrupted and sres.signal_name == "SIGTERM"
    assert "--resume auto" in sres.resume_command
    assert "--supervise" in sres.resume_command
    # the flushed checkpoint is loadable, and resuming from it finishes
    # the run bitwise-identically to the uninterrupted one
    p = latest_checkpoint(stem)
    assert p is not None
    grid, step, _ = load_checkpoint(p, HeatConfig(steps=100, **_BASE))
    assert step == sres.steps_done
    sres2 = run_supervised(HeatConfig(steps=100 - step, **_BASE), stem,
                           policy=_policy(), initial=grid,
                           start_step=step)
    assert not sres2.interrupted and sres2.steps_done == 100
    np.testing.assert_array_equal(sres2.result.to_numpy(),
                                  clean.to_numpy())


def test_sigint_is_absorbed_and_handlers_restored(tmp_path):
    before = signal.getsignal(signal.SIGINT)
    sres = run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                          policy=_policy(),
                          faults=FaultPlan(signal_at_chunk=2,
                                           signum=int(signal.SIGINT)))
    assert sres.interrupted and sres.signal_name == "SIGINT"
    assert signal.getsignal(signal.SIGINT) is before


def test_supervisor_converge_mode_stops_early_and_checkpoints(tmp_path):
    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, backend="jnp")
    direct = solve(cfg)
    sres = run_supervised(cfg, tmp_path / "ck",
                          policy=_policy(checkpoint_every=500,
                                         guard_interval=100))
    assert sres.result.converged
    assert sres.steps_done == direct.steps_run
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  direct.to_numpy())
    # the convergence point itself was checkpointed
    assert [s for s, _ in generation_paths(tmp_path / "ck")][-1] \
        == direct.steps_run


def test_supervisor_sharded_run_with_rollback(tmp_path):
    kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 2))
    clean = solve(HeatConfig(steps=60, **kw))
    sres = run_supervised(HeatConfig(steps=60, **kw), tmp_path / "ck",
                          policy=_policy(),
                          faults=FaultPlan(nan_at_step=35))
    assert sres.rollbacks == 1
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_supervisor_f32chunk_requires_aligned_cadence(tmp_path):
    cfg = HeatConfig(nx=16, ny=128, steps=64, backend="jnp",
                     dtype="bfloat16", accumulate="f32chunk")
    with pytest.raises(ValueError, match="multiples of the chunk depth"):
        run_supervised(cfg, tmp_path / "ck",
                       policy=_policy(checkpoint_every=10))
    # aligned cadence streams bitwise like the one-shot run
    clean = solve(cfg)
    sres = run_supervised(cfg, tmp_path / "ck",
                          policy=_policy(checkpoint_every=32,
                                         guard_interval=16))
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_resume_command_round_trips_non_default_flags(tmp_path):
    # The printed resume command must reproduce the run it resumes:
    # schedule-affecting flags (--no-overlap) and deliverables (--out)
    # included, --initial-out excluded (a resumed run's `initial` is
    # checkpoint state, not t=0).
    cfg = HeatConfig(steps=60, overlap=False, dtype="bfloat16", **_BASE)
    sres = run_supervised(cfg, tmp_path / "ck", policy=_policy(),
                          faults=FaultPlan(signal_at_chunk=2))
    cmd = sres.resume_command
    assert "--no-overlap" in cmd and "--dtype bfloat16" in cmd
    assert "--steps 60" in cmd and "--backend jnp" in cmd


def test_cli_supervise_f32chunk_default_cadence_aligns(tmp_path):
    from parallel_heat_tpu.cli import main

    # steps//10 = 10 is not a multiple of bf16's K=16; the DEFAULT
    # cadence must round itself up instead of crashing...
    assert main(["--nx", "16", "--ny", "128", "--steps", "100",
                 "--dtype", "bfloat16", "--accumulate", "f32chunk",
                 "--backend", "jnp", "--supervise",
                 "--checkpoint", str(tmp_path / "ck"), "--quiet"]) == 0
    # ...while an EXPLICIT misaligned cadence fails with a clean
    # one-line CLI error, not a traceback
    assert main(["--nx", "16", "--ny", "128", "--steps", "100",
                 "--dtype", "bfloat16", "--accumulate", "f32chunk",
                 "--backend", "jnp", "--supervise",
                 "--checkpoint", str(tmp_path / "ck2"),
                 "--checkpoint-every", "10", "--quiet"]) == 2


def test_nan_guard_trip_lands_in_telemetry_within_one_interval(tmp_path):
    # The ISSUE 3 chaos satellite: a NaN injection must surface in the
    # telemetry EVENT STREAM (not just the SupervisorResult) within one
    # guard_interval of the corruption step — CI asserts on the
    # artifact, no stdout scraping.
    import json

    k = 35
    p = tmp_path / "t.jsonl"
    with Telemetry(p) as tel:
        run_supervised(HeatConfig(steps=60, **_BASE), tmp_path / "ck",
                       policy=_policy(), telemetry=tel,
                       faults=FaultPlan(nan_at_step=k))
    with open(p) as f:
        events = [json.loads(line) for line in f if line.strip()]
    trips = [e for e in events if e["event"] == "guard_trip"]
    assert len(trips) == 1
    assert 0 < trips[0]["step"] - k <= 10  # one guard_interval
    lo, hi = trips[0]["window"]
    assert lo < k <= hi


def test_sigterm_with_async_save_in_flight_resumes_bitwise(tmp_path):
    # ISSUE 5's new failure window: SIGTERM lands while an async
    # checkpoint is IN FLIGHT (throttled saver holds every commit
    # open). The interrupt barrier must drain it, the flushed state
    # must land, and the resume from the last COMMITTED generation
    # must finish bitwise like the uninterrupted run.
    from parallel_heat_tpu.utils.checkpoint import AsyncCheckpointer

    clean = solve(HeatConfig(steps=100, **_BASE))
    stem = tmp_path / "ck"
    saver = AsyncCheckpointer(keep=3, throttle_s=0.05)
    try:
        sres = run_supervised(HeatConfig(steps=100, **_BASE), stem,
                              policy=_policy(), checkpointer=saver,
                              faults=FaultPlan(
                                  signal_at_chunk=3,
                                  signum=int(signal.SIGTERM)))
        assert sres.interrupted and sres.signal_name == "SIGTERM"
        p = latest_checkpoint(stem)
        assert p is not None
        grid, step, _ = load_checkpoint(p, HeatConfig(steps=100, **_BASE))
        assert step == sres.steps_done  # the flush COMMITTED
        sres2 = run_supervised(HeatConfig(steps=100 - step, **_BASE),
                               stem, policy=_policy(), initial=grid,
                               start_step=step, checkpointer=saver)
    finally:
        saver.close()
    assert sres2.steps_done == 100
    np.testing.assert_array_equal(sres2.result.to_numpy(),
                                  clean.to_numpy())


def test_guard_trip_racing_async_save_never_restores_uncommitted(
        tmp_path):
    # The rollback barrier: a NaN trip with the previous boundary's
    # save still in flight must drain BEFORE generation discovery —
    # the telemetry stream shows checkpoint_barrier(reason=rollback)
    # strictly before the rollback event, and recovery is bitwise.
    import json

    from parallel_heat_tpu.utils.checkpoint import AsyncCheckpointer

    clean = solve(HeatConfig(steps=60, **_BASE))
    p = tmp_path / "t.jsonl"
    saver = AsyncCheckpointer(keep=3, throttle_s=0.05)
    try:
        with Telemetry(p) as tel:
            sres = run_supervised(HeatConfig(steps=60, **_BASE),
                                  tmp_path / "ck", policy=_policy(),
                                  checkpointer=saver, telemetry=tel,
                                  faults=FaultPlan(nan_at_step=35))
    finally:
        saver.close()
    assert sres.retries == 1 and sres.rollbacks == 1
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())
    with open(p) as f:
        events = [json.loads(line) for line in f if line.strip()]
    rb_idx = next(i for i, e in enumerate(events)
                  if e["event"] == "rollback")
    assert any(e["event"] == "checkpoint_barrier"
               and e["reason"] == "rollback"
               for e in events[:rb_idx])
    # the rollback landed on a committed generation at-or-before the
    # corruption step
    rb = events[rb_idx]
    assert rb["step"] < 35


def test_supervised_pipelined_stream_recovers_bitwise(tmp_path):
    # The chaos bitwise-resume contract extended to pipeline_depth=2
    # explicitly: supervised runs over the dispatch-ahead stream (with
    # the async saver on, the default) recover from a mid-run NaN
    # bitwise like the depth-1 loop does.
    cfg = HeatConfig(steps=60, pipeline_depth=2, **_BASE)
    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(cfg, tmp_path / "ck", policy=_policy(),
                          faults=FaultPlan(nan_at_step=35))
    assert sres.retries == 1 and sres.steps_done == 60
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_supervised_pipelined_sigterm_resume_bitwise(tmp_path):
    cfg = HeatConfig(steps=100, pipeline_depth=2, **_BASE)
    clean = solve(HeatConfig(steps=100, **_BASE))
    stem = tmp_path / "ck"
    sres = run_supervised(cfg, stem, policy=_policy(),
                          faults=FaultPlan(signal_at_chunk=3,
                                           signum=int(signal.SIGTERM)))
    assert sres.interrupted
    assert "--pipeline-depth 2" in sres.resume_command
    grid, step, _ = load_checkpoint(latest_checkpoint(stem), cfg)
    sres2 = run_supervised(cfg.replace(steps=100 - step), stem,
                           policy=_policy(), initial=grid,
                           start_step=step)
    assert sres2.steps_done == 100
    np.testing.assert_array_equal(sres2.result.to_numpy(),
                                  clean.to_numpy())


def test_fault_plan_determinism():
    plan = FaultPlan(transient_on_chunks=(1,))
    assert plan.before_chunk() == 0
    with pytest.raises(InjectedTransientError):
        plan.before_chunk()
    # one-shot: the retried ordinal stream does not re-fire
    assert plan.before_chunk() == 2


# ---------------------------------------------------------------------------
# CLI integration
# ---------------------------------------------------------------------------

def test_cli_supervise_resume_auto_bitwise(tmp_path):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    ck = tmp_path / "ck"
    assert main(["--nx", "16", "--ny", "16", "--steps", "40",
                 "--backend", "jnp", "--supervise",
                 "--checkpoint", str(ck), "--checkpoint-every", "10",
                 "--guard-interval", "5", "--quiet"]) == 0
    assert latest_checkpoint(ck) is not None
    out = tmp_path / "resumed.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "60",
                 "--backend", "jnp", "--supervise",
                 "--checkpoint", str(ck), "--resume", "auto",
                 "--out", str(out), "--quiet"]) == 0
    direct = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "60",
                 "--backend", "jnp", "--out", str(direct),
                 "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(direct))


def test_cli_supervise_requires_checkpoint(capsys):
    from parallel_heat_tpu.cli import main

    assert main(["--nx", "12", "--ny", "12", "--steps", "10",
                 "--supervise"]) == 2
    assert "--supervise requires --checkpoint" in capsys.readouterr().err


def test_cli_resume_auto_requires_checkpoint(capsys):
    from parallel_heat_tpu.cli import main

    assert main(["--nx", "12", "--ny", "12", "--steps", "10",
                 "--resume", "auto"]) == 2
    assert "--resume auto requires --checkpoint" in capsys.readouterr().err


def test_cli_resume_auto_fresh_start_when_no_checkpoint(tmp_path):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    out = tmp_path / "fresh.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "20",
                 "--backend", "jnp", "--checkpoint",
                 str(tmp_path / "none"), "--resume", "auto",
                 "--out", str(out), "--quiet"]) == 0
    direct = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "20",
                 "--backend", "jnp", "--out", str(direct),
                 "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(direct))


def test_cli_permanent_failure_exit_code(tmp_path, capsys):
    from parallel_heat_tpu.cli import main

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rc = main(["--nx", "16", "--ny", "16", "--steps", "100",
                   "--cx", "5.0", "--cy", "5.0", "--backend", "jnp",
                   "--supervise", "--checkpoint",
                   str(tmp_path / "ck"), "--checkpoint-every", "10",
                   "--quiet"])
    assert rc == EXIT_PERMANENT_FAILURE
    assert "permanent failure" in capsys.readouterr().err


def test_exit_code_constants_are_the_documented_contract():
    # Restart loops in the wild already branch on 3/4 (README run-book);
    # the named constants must never drift from those values, and must
    # stay distinct from argparse's 2.
    assert EXIT_PREEMPTED == 3
    assert EXIT_PERMANENT_FAILURE == 4


def test_guard_env_does_not_change_compiled_programs():
    # The guard must reuse the unguarded config's compiled executables:
    # stripping guard_interval keys both runs to the same cache entry.
    from parallel_heat_tpu import solver

    cfg = HeatConfig(steps=20, **_BASE)
    solver._build_runner.cache_clear()
    solve(cfg)
    misses_before = solver._build_runner.cache_info().misses
    solve(cfg.replace(guard_interval=5))
    assert solver._build_runner.cache_info().misses == misses_before


# ---------------------------------------------------------------------------
# Injectable backoff clock (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

def test_backoff_schedule_pinned_via_sleep_fn(tmp_path):
    # The bounded-exponential retry schedule, deterministic: sleep_fn
    # records every backoff delay instead of sleeping wall-clock —
    # min(backoff_max_s, backoff_base_s * 2**(retry-1)).
    delays = []
    policy = _policy(backoff_base_s=0.5, backoff_max_s=1.0,
                     max_retries=3, sleep_fn=delays.append)
    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(HeatConfig(steps=60, **_BASE),
                          tmp_path / "ck", policy=policy,
                          faults=FaultPlan(transient_on_chunks=(0, 1,
                                                                2)))
    assert sres.retries == 3
    assert delays == [0.5, 1.0, 1.0]  # 2**2*0.5 clamped to the bound
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


def test_backoff_zero_base_never_calls_sleep(tmp_path):
    # delay == 0 skips the sleep call entirely (tests and the chaos
    # matrix run with base 0 — they must not depend on sleep_fn(0)).
    delays = []
    sres = run_supervised(
        HeatConfig(steps=60, **_BASE), tmp_path / "ck",
        policy=_policy(sleep_fn=delays.append),
        faults=FaultPlan(transient_on_chunks=(1,)))
    assert sres.retries == 1 and delays == []


def test_policy_clock_injectable_for_wall_bookkeeping(tmp_path):
    # `clock` feeds wall_s bookkeeping only (observation, never
    # numerics): a fake clock yields exact wall arithmetic while the
    # grid stays bitwise the real-clock run's.
    t = {"now": 100.0}

    def clock():
        t["now"] += 0.125
        return t["now"]

    clean = solve(HeatConfig(steps=60, **_BASE))
    sres = run_supervised(HeatConfig(steps=60, **_BASE),
                          tmp_path / "ck",
                          policy=_policy(clock=clock))
    assert sres.wall_s > 0 and sres.wall_s % 0.125 == 0
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


# ---------------------------------------------------------------------------
# Checkpoint stem interlock (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

def test_stem_lock_refuses_concurrent_supervised_runs(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import (
        StemLockError,
        acquire_stem_lock,
        checkpoint_stem,
    )

    stem = tmp_path / "ck"
    release = acquire_stem_lock(checkpoint_stem(stem))
    # A second supervised run on the same stem fails actionably at
    # startup — before it can prune or roll back to the holder's
    # generations.
    with pytest.raises(StemLockError) as ei:
        run_supervised(HeatConfig(steps=20, **_BASE), stem,
                       policy=_policy())
    msg = str(ei.value)
    assert str(os.getpid()) in msg  # names the live holder
    assert "different" in msg and ".lock" in msg  # names the way out
    assert latest_checkpoint(stem) is None  # wrote nothing
    release()
    sres = run_supervised(HeatConfig(steps=20, **_BASE), stem,
                          policy=_policy())
    assert sres.steps_done == 20


def test_stem_lock_stale_holder_reclaimed(tmp_path):
    import json as _json

    from parallel_heat_tpu.utils.checkpoint import _stem_lock_path

    stem = tmp_path / "ck"
    os.makedirs(tmp_path, exist_ok=True)
    # A SIGKILLed predecessor left its lockfile; its pid is dead.
    with open(_stem_lock_path(str(stem)), "w") as f:
        _json.dump({"pid": 2 ** 22 + 1, "t_wall": 0.0}, f)
    sres = run_supervised(HeatConfig(steps=20, **_BASE), stem,
                          policy=_policy())
    assert sres.steps_done == 20  # reclaimed, ran, and...
    assert not os.path.exists(_stem_lock_path(str(stem)))  # released


def test_stem_lock_released_after_failure(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import _stem_lock_path

    stem = tmp_path / "ck"
    with pytest.raises(PermanentFailure):
        run_supervised(HeatConfig(steps=60, **_BASE), stem,
                       policy=_policy(max_retries=1),
                       faults=FaultPlan(nan_at_step=35, recurring=True))
    # the lock must not outlive the run — a crash-halt that wedged the
    # stem would block its own `--resume auto`
    assert not os.path.exists(_stem_lock_path(str(stem)))
    sres = run_supervised(HeatConfig(steps=20, **_BASE), stem,
                          policy=_policy())
    assert sres.steps_done > 0


def test_stem_lock_torn_lockfile_treated_stale(tmp_path):
    from parallel_heat_tpu.utils.checkpoint import (
        _stem_lock_path,
        acquire_stem_lock,
        checkpoint_stem,
    )

    stem = str(tmp_path / "ck")
    with open(_stem_lock_path(stem), "w") as f:
        f.write('{"pid": 12')  # writer died mid-write
    release = acquire_stem_lock(checkpoint_stem(stem))
    release()


# ---------------------------------------------------------------------------
# FaultPlan.kill_worker_at_chunk (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

def test_faultplan_kill_worker_rejects_in_process_kinds():
    # SIGKILL ends the process: combining it with any in-process fault
    # either masks the death or certifies a detection that never ran —
    # loud, like nan+spike.
    FaultPlan(kill_worker_at_chunk=2)  # alone: fine
    for bad in (dict(nan_at_step=3), dict(spike_at_step=3),
                dict(transient_on_chunks=(1,)),
                dict(signal_at_chunk=1)):
        with pytest.raises(ValueError, match="kill_worker_at_chunk"):
            FaultPlan(kill_worker_at_chunk=2, **bad)


# ---------------------------------------------------------------------------
# Service-level chaos: the heatd durability contract (ISSUE 8)
# ---------------------------------------------------------------------------

def _service_daemon(root, **kw):
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig

    kw.setdefault("slots", 1)
    kw.setdefault("worker_heartbeat_s", 0.25)
    kw.setdefault("heartbeat_timeout_s", 1.0)
    kw.setdefault("requeue_backoff_base_s", 0.0)
    kw.setdefault("worker_env", {"JAX_PLATFORMS": "cpu"})
    return Heatd(HeatdConfig(root=str(root), **kw))


def _service_spec(job_id, **kw):
    from parallel_heat_tpu.service.store import JobSpec

    kw.setdefault("checkpoint_every", 10)
    kw.setdefault("guard_interval", 5)
    kw.setdefault("backoff_base_s", 0.0)
    return JobSpec(job_id=job_id,
                   config={"nx": 16, "ny": 16, "steps": 60,
                           "backend": "jnp"}, **kw)


def _drive_daemon(daemon, done, timeout_s=240.0):
    import time as _time

    t0 = _time.monotonic()
    while _time.monotonic() - t0 < timeout_s:
        daemon.step()
        jobs, anomalies = daemon.store.replay()
        if done(jobs):
            return jobs, anomalies
        _time.sleep(0.03)
    raise TimeoutError("daemon did not reach the expected state")


def test_service_worker_sigkill_orphaned_requeued_bitwise(tmp_path):
    # THE durability proof: a real worker subprocess SIGKILLs itself
    # mid-job (no flush, no record). The job must be detected orphaned
    # within one heartbeat timeout, requeued with its checkpoint
    # lineage intact, and the re-dispatched attempt must complete with
    # a grid bitwise identical to an uninterrupted run.
    import time as _time

    root = tmp_path / "q"
    hb_timeout = 1.0
    d1 = _service_daemon(root, heartbeat_timeout_s=hb_timeout)
    d1.store.spool_submit(_service_spec(
        "j1", faults={"kill_worker_at_chunk": 4}, faults_on_attempt=1))
    jobs, _ = _drive_daemon(d1, lambda j: "j1" in j
                            and j["j1"].state == "running")
    # Reap the corpse via d1's handle (init's role for a real daemon's
    # orphans) without journaling anything — detection must come from
    # the restarted daemon's heartbeat/pid judgment alone.
    handle = d1._procs["j1"]
    t0 = _time.monotonic()
    while handle.poll() is None and _time.monotonic() - t0 < 180:
        _time.sleep(0.05)
    assert handle.poll() == -signal.SIGKILL  # true process death
    d1.store.close()

    d2 = _service_daemon(root, heartbeat_timeout_s=hb_timeout)
    jobs, anomalies = _drive_daemon(d2, lambda j: j["j1"].terminal)
    assert anomalies == []  # no double terminal, nothing lost
    assert jobs["j1"].state == "completed"
    assert jobs["j1"].attempts == 2
    events, _, _ = d2.store.read_journal()
    orphaned = [e for e in events if e.get("event") == "orphaned"]
    assert len(orphaned) == 1
    # detected within one heartbeat timeout of the last proven beat
    hb = d2.store.read_worker_hb(orphaned[0]["worker"])
    lag = orphaned[0]["t_wall"] - hb["t_wall"]
    assert lag <= hb_timeout + 1.0  # + scheduling slack
    assert any(e.get("event") == "requeued" for e in events)
    # bitwise: the resumed trajectory IS the uninterrupted one
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint as _latest,
        load_checkpoint as _load,
    )

    cfg = HeatConfig(steps=60, **_BASE)
    grid, step, _ = _load(_latest(d2.store.checkpoint_stem("j1")), cfg)
    assert step == 60
    np.testing.assert_array_equal(np.asarray(grid),
                                  solve(cfg).to_numpy())
    d2.store.close()


def test_service_daemon_sigkill_between_accept_and_dispatch(tmp_path):
    # The daemon itself dies (SIGKILL — no drain, no cleanup) right
    # after journaling `accepted`, before dispatch and before the
    # spool unlink. Restart must recover the job from the journal
    # alone: exactly one terminal state, no loss, no re-accept.
    import subprocess as _sp

    from parallel_heat_tpu.service import client as svc_client

    root = str(tmp_path / "q")
    import parallel_heat_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": pkg_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    daemon = _sp.Popen(
        [sys.executable, "-m", "parallel_heat_tpu.cli", "serve",
         "--queue", root, "--slots", "1", "--poll-interval", "0.1",
         "--chaos-kill-after-accept", "1"],
        env=env, stdout=_sp.DEVNULL, stderr=_sp.STDOUT)
    try:
        v = svc_client.submit(root, {"nx": 16, "ny": 16, "steps": 60,
                                     "backend": "jnp"},
                              job_id="j1", checkpoint_every=10,
                              backoff_base_s=0.0, accept_timeout_s=120)
        assert v["accepted"] is True
        daemon.wait(timeout=60)
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()
    assert daemon.returncode == -signal.SIGKILL

    d2 = _service_daemon(root)
    jobs, anomalies = _drive_daemon(d2, lambda j: j["j1"].terminal)
    assert anomalies == []
    assert jobs["j1"].state == "completed"
    events, _, _ = d2.store.read_journal()
    accepts = [e for e in events if e.get("event") == "accepted"]
    assert len(accepts) == 1  # idempotent handshake, no re-accept
    assert d2.store.iter_spool() == []
    d2.store.close()


def test_service_overload_rejects_never_drops(tmp_path):
    # Overload burst past the admission gate: rejected with a
    # retry-after hint, never accepted-then-dropped; the admitted jobs
    # complete bitwise through real (inline) execution.
    from parallel_heat_tpu.service.harness import inline_launcher
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint as _latest,
        load_checkpoint as _load,
    )

    root = str(tmp_path / "q")
    # defer=10: the handle stays 'running' for several polls before
    # executing — deterministic queue occupancy, so the burst actually
    # finds the gate closed (instant inline completion would drain it).
    d = _service_daemon(root, launcher=inline_launcher(root, defer=10),
                        max_queue_depth=2, worker_env=None)
    for i in range(5):
        d.store.spool_submit(_service_spec(f"j{i}"))
        d.step()
    jobs, _ = d.store.replay()
    rejected = {j for j, v in jobs.items() if v.state == "rejected"}
    admitted = [j for j, v in jobs.items() if v.state != "rejected"]
    assert len(rejected) == 3 and len(admitted) == 2
    assert all(jobs[j].retry_after_s > 0 for j in rejected)
    jobs, anomalies = _drive_daemon(
        d, lambda j: all(j[a].terminal for a in admitted))
    assert anomalies == []
    assert all(jobs[a].state == "completed" for a in admitted)
    # a rejected job never acquires execution state
    events, _, _ = d.store.read_journal()
    assert not any(e.get("job_id") in rejected
                   and e.get("event") != "rejected" for e in events)
    cfg = HeatConfig(steps=60, **_BASE)
    clean = solve(cfg).to_numpy()
    for a in admitted:
        grid, _, _ = _load(_latest(d.store.checkpoint_stem(a)), cfg)
        np.testing.assert_array_equal(np.asarray(grid), clean)
    d.store.close()


def test_service_deadline_interrupts_through_supervisor(tmp_path):
    # A deadline that expires mid-run interrupts through the
    # supervisor's flag-only path: checkpoint flushed, preempted
    # record with reason "deadline", journaled deadline_expired —
    # with the partial progress durable.
    from parallel_heat_tpu.service.harness import inline_launcher
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint as _latest,
    )

    root = str(tmp_path / "q")
    d = _service_daemon(root, launcher=inline_launcher(root),
                        worker_env=None)
    # deadline passes before the worker's first boundary poll: the
    # supervisor flushes generation 0+ and exits preempted(deadline)
    d.store.spool_submit(_service_spec("j1", deadline_s=0.05))
    import time as _time

    _time.sleep(0.1)
    jobs, anomalies = _drive_daemon(d, lambda j: "j1" in j
                                    and j["j1"].terminal)
    assert anomalies == []
    assert jobs["j1"].state == "deadline_expired"
    rec = d.store.read_result("j1", 1)
    if rec is not None:  # expired while running (not while queued)
        assert rec["outcome"] == "preempted"
        assert rec["reason"] == "deadline"
    # the flushed checkpoint lineage is durable either way
    assert _latest(d.store.checkpoint_stem("j1")) is not None or \
        rec is None
    d.store.close()
