import numpy as np

from parallel_heat_tpu.cli import main
from parallel_heat_tpu.utils.io import read_dat


def test_cli_fixed_run_writes_dat(tmp_path, capsys):
    out = tmp_path / "final_im.dat"
    init = tmp_path / "initial_im.dat"
    rc = main(["--nx", "20", "--ny", "20", "--steps", "50",
               "--backend", "jnp", "--out", str(out),
               "--initial-out", str(init)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "Grid size: 20x20  Time steps: 50" in text
    assert "Elapsed time" in text
    assert out.exists() and init.exists()
    assert read_dat(out).shape == (20, 20)


def test_cli_converge_reports_steps(capsys):
    rc = main(["--nx", "20", "--ny", "20", "--steps", "10000",
               "--converge", "--backend", "jnp"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "Converged after" in text


def test_cli_mesh_run(capsys):
    rc = main(["--nx", "32", "--ny", "32", "--steps", "10",
               "--backend", "jnp", "--mesh", "2,4", "--quiet"])
    assert rc == 0
    assert capsys.readouterr().out == ""


def test_cli_rejects_bad_config(capsys):
    rc = main(["--nx", "20", "--ny", "20", "--mesh", "3,1",
               "--backend", "jnp"])
    assert rc == 2
    assert "not divisible" in capsys.readouterr().err


def test_cli_3d_npy_output(tmp_path):
    out = tmp_path / "vol.npy"
    rc = main(["--nx", "8", "--ny", "8", "--nz", "8", "--steps", "3",
               "--backend", "jnp", "--out", str(out), "--quiet"])
    assert rc == 0
    assert np.load(out).shape == (8, 8, 8)


def test_cli_checkpoint_resume_matches_uninterrupted(tmp_path, capsys):
    ck = tmp_path / "ck.npz"
    # run 30 steps, checkpointing
    assert main(["--nx", "16", "--ny", "16", "--steps", "30",
                 "--backend", "jnp", "--checkpoint", str(ck),
                 "--quiet"]) == 0
    # resume to 50 total
    out = tmp_path / "resumed.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--resume", str(ck),
                 "--out", str(out), "--quiet"]) == 0
    # uninterrupted 50
    out2 = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "50",
                 "--backend", "jnp", "--out", str(out2), "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(out2))


def test_cli_checkpoint_every(tmp_path, capsys):
    ck = tmp_path / "live.npz"
    rc = main(["--nx", "16", "--ny", "16", "--steps", "50",
               "--backend", "jnp", "--checkpoint", str(ck),
               "--checkpoint-every", "20"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("Checkpoint at step") == 3  # 20, 40, 50
    from parallel_heat_tpu.utils.checkpoint import load_checkpoint

    grid, step, _ = load_checkpoint(ck)
    assert step == 50
    from parallel_heat_tpu import HeatConfig, solve

    direct = solve(HeatConfig(nx=16, ny=16, steps=50, backend="jnp"))
    np.testing.assert_array_equal(grid, direct.to_numpy())


def test_cli_checkpoint_every_requires_checkpoint():
    rc = main(["--nx", "16", "--ny", "16", "--steps", "50",
               "--backend", "jnp", "--checkpoint-every", "20"])
    assert rc == 2


def test_cli_checkpoint_every_rejects_nonpositive(tmp_path):
    rc = main(["--nx", "16", "--ny", "16", "--steps", "50",
               "--backend", "jnp", "--checkpoint", str(tmp_path / "c.npz"),
               "--checkpoint-every", "-8"])
    assert rc == 2


def test_example_cooling_plate(tmp_path, monkeypatch, capsys):
    import importlib.util
    import os
    import sys

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "cooling_plate.py")
    spec = importlib.util.spec_from_file_location("cooling_plate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(sys, "argv", [
        "cooling_plate.py", "--nx", "16", "--ny", "16", "--steps", "200",
        "--snapshots", "2", "--out", str(tmp_path / "out")])
    mod.main()
    out = capsys.readouterr().out
    assert "state checkpointed" in out
    names = sorted(p.name for p in (tmp_path / "out").iterdir())
    assert "initial.dat" in names and "final.dat" in names
    assert "state.npz" in names
    assert any(n.startswith("snap_") for n in names)


def test_cli_halo_depth_auto(tmp_path):
    import jax

    n = len(jax.devices())
    if n < 4:
        import pytest
        pytest.skip("needs a multi-device mesh")
    # auto on a mesh -> sublane depth; auto single-device -> 1
    rc = main(["--nx", "32", "--ny", "32", "--steps", "8",
               "--backend", "jnp", "--mesh", "2,2",
               "--halo-depth", "auto", "--quiet",
               "--out", str(tmp_path / "a.dat")])
    assert rc == 0
    rc = main(["--nx", "32", "--ny", "32", "--steps", "8",
               "--backend", "jnp", "--halo-depth", "auto", "--quiet"])
    assert rc == 0
    rc = main(["--nx", "32", "--ny", "32", "--halo-depth", "bogus"])
    assert rc == 2


def test_cli_halo_depth_auto_clamps_to_block(tmp_path):
    import jax

    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("needs a multi-device mesh")
    # bf16 auto would be 16, but 20/2 = 10-cell blocks -> clamped, runs
    rc = main(["--nx", "20", "--ny", "20", "--steps", "4",
               "--dtype", "bfloat16", "--backend", "jnp",
               "--mesh", "2,2", "--halo-depth", "auto", "--quiet"])
    assert rc == 0
    # explicit pallas with a clamped depth falls back to depth 1
    rc = main(["--nx", "20", "--ny", "20", "--steps", "4",
               "--dtype", "bfloat16", "--backend", "pallas",
               "--mesh", "2,2", "--halo-depth", "auto", "--quiet"])
    assert rc == 0


def test_explain_flag(capsys):
    from parallel_heat_tpu.cli import main

    assert main(["--nx", "64", "--ny", "64", "--explain"]) == 0
    out = capsys.readouterr().out
    assert "path:" in out and "backend:" in out


def test_explain_resolves_expected_paths():
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.solver import explain

    # Mirrors the picker decision order without running anything.
    assert "kernel A" in explain(
        HeatConfig(nx=256, ny=256, backend="pallas"))["path"]
    assert "kernel E" in explain(
        HeatConfig(nx=16384, ny=16384, backend="pallas"))["path"]
    assert "kernel F" in explain(
        HeatConfig(nx=512, ny=512, nz=512, backend="pallas"))["path"]
    assert "kernel G" in explain(
        HeatConfig(nx=256, ny=256, mesh_shape=(2, 4), backend="pallas",
                   halo_depth=8))["path"]
    assert "jnp" in explain(
        HeatConfig(nx=64, ny=64, backend="jnp"))["path"]


def test_explain_reports_uniform_kinds(monkeypatch):
    # The uniform-gather variants must surface in --explain with their
    # geometry, storage and f32chunk branches both (same decision site
    # as execution — pick_single_2d). Hardware alignment rules pinned:
    # kernel I's interpret-mode column halo (2*SUB, not a lane tile)
    # puts the 32768^2 tile under the wide-row knee on CPU, and the
    # production decision is the hardware one (picks never build).
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.ops import pallas_stencil as ps
    from parallel_heat_tpu.solver import explain

    monkeypatch.setattr(ps, "_needs_lane_alignment", lambda: True)
    p = explain(HeatConfig(nx=16384, ny=16384, backend="pallas"))["path"]
    assert "kernel E-uni" in p and "T=" in p
    p = explain(HeatConfig(nx=32768, ny=32768, dtype="bfloat16",
                           backend="pallas"))["path"]
    assert "kernel I-uni" in p and "tile=" in p
    p = explain(HeatConfig(nx=16384, ny=16384, dtype="bfloat16",
                           backend="pallas",
                           accumulate="f32chunk"))["path"]
    assert "kernel E-uni" in p and "f32-chunk" in p
    # below the wide-row knee the incumbent keeps the pick
    p = explain(HeatConfig(nx=8192, ny=8192, backend="pallas"))["path"]
    assert "kernel E " in p or p.startswith("kernel E (")


def test_explain_sharded_tiled_fallback():
    # block_steps' fallback order is strip -> tiled -> jnp; explain()
    # must mirror all three (regression: the tiled stage was omitted,
    # misreporting exactly the decline cases --explain exists for).
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.solver import explain

    path = explain(HeatConfig(nx=1024, ny=524288, mesh_shape=(2, 2),
                              backend="pallas", dtype="bfloat16"))["path"]
    assert "kernel C" in path
