"""Telemetry subsystem: JSONL event stream, heartbeat, and the
observation-only contract (telemetry shares the un-instrumented runs'
compiled executables, bitwise — the guard's contract extended to
instrumentation, SEMANTICS.md)."""

import json
import os
import warnings

import numpy as np
import pytest

from parallel_heat_tpu import (
    HeatConfig,
    SupervisorPolicy,
    Telemetry,
    run_supervised,
    solve,
    solve_stream,
)
from parallel_heat_tpu.utils.faults import FaultPlan
from parallel_heat_tpu.utils.telemetry import SCHEMA_VERSION

_BASE = dict(nx=16, ny=16, backend="jnp")


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_stream_emits_header_and_chunk_events(tmp_path):
    p = tmp_path / "t.jsonl"
    with Telemetry(p) as tel:
        for _ in solve_stream(HeatConfig(steps=30, **_BASE),
                              chunk_steps=10, telemetry=tel):
            pass
    ev = _events(p)
    # Every chunk is followed by its prof-plane attribution segment
    # (tests/test_prof.py pins the profile payload itself).
    assert [e["event"] for e in ev] == \
        ["run_header"] + ["chunk", "profile"] * 3
    ev = [e for e in ev if e["event"] != "profile"]
    # envelope on every record
    for e in ev:
        assert e["schema"] == SCHEMA_VERSION
        assert e["t_wall"] > 0 and e["t_mono"] > 0
    hdr = ev[0]
    assert hdr["config"]["nx"] == 16 and hdr["config"]["steps"] == 30
    assert hdr["explain"]["backend"] == "jnp"
    assert hdr["platform"] == "cpu" and hdr["device_count"] == 8
    assert "jax_version" in hdr
    chunks = ev[1:]
    assert [c["step"] for c in chunks] == [10, 20, 30]
    assert all(c["steps"] == 10 for c in chunks)
    assert all(c["wall_s"] >= 0 for c in chunks)
    assert all(c["cells"] == 256 for c in chunks)
    # f32: one read + one write per cell per step
    assert all(c["bytes_per_cell"] == 8 for c in chunks)
    # rates come from StepStats (None only if the wall time was 0)
    for c in chunks:
        if c["wall_s"] > 0:
            assert c["steps_per_s"] == pytest.approx(
                c["steps"] / c["wall_s"])
            assert c["hbm_gb_s"] > 0


def test_stream_chunk_events_carry_residual_and_guard(tmp_path):
    p = tmp_path / "t.jsonl"
    cfg = HeatConfig(nx=12, ny=12, steps=10_000, converge=True,
                     check_interval=20, guard_interval=20, backend="jnp")
    with Telemetry(p) as tel:
        for _ in solve_stream(cfg, chunk_steps=20, telemetry=tel):
            pass
    chunks = [e for e in _events(p) if e["event"] == "chunk"]
    assert all(c["residual"] is not None for c in chunks)
    assert all(c["finite"] is True for c in chunks)
    assert chunks[-1]["converged"] is True


def test_supervised_run_covers_all_event_families(tmp_path):
    p = tmp_path / "t.jsonl"
    with Telemetry(p, heartbeat=tmp_path / "hb.json") as tel:
        sres = run_supervised(
            HeatConfig(steps=60, **_BASE), tmp_path / "ck",
            policy=SupervisorPolicy(checkpoint_every=20,
                                    guard_interval=10,
                                    backoff_base_s=0.0),
            faults=FaultPlan(nan_at_step=35), telemetry=tel)
    assert sres.retries == 1
    ev = _events(p)
    kinds = {e["event"] for e in ev}
    assert {"run_header", "chunk", "checkpoint_save", "guard_trip",
            "retry", "rollback", "run_end"} <= kinds
    # exactly one header despite the rollback's second stream segment
    assert sum(1 for e in ev if e["event"] == "run_header") == 1
    # chunk steps are ABSOLUTE: the rollback (to the step-20 retained
    # generation) re-walks 30..60, not 10..40 again
    steps = [e["step"] for e in ev if e["event"] == "chunk"]
    assert steps == [10, 20, 30, 40, 30, 40, 50, 60]
    trip = next(e for e in ev if e["event"] == "guard_trip")
    assert trip["step"] == 40 and trip["window"] == [30, 40]
    saves = [e for e in ev if e["event"] == "checkpoint_save"]
    assert all(s["wall_s"] >= 0 for s in saves)
    assert [s["step"] for s in saves][:2] == [0, 20]
    rb = next(e for e in ev if e["event"] == "rollback")
    assert rb["step"] < 35 and rb["load_wall_s"] >= 0
    end = ev[-1]
    assert end["event"] == "run_end" and end["outcome"] == "complete"
    assert end["steps_done"] == 60 and end["retries"] == 1
    # heartbeat: atomic JSON doc, no torn write, current
    hb = json.load(open(tmp_path / "hb.json"))
    assert hb["pid"] == os.getpid()
    assert hb["last_event"] == "run_end" and hb["events"] == len(ev)


def test_supervised_permanent_failure_emits_run_end(tmp_path):
    from parallel_heat_tpu import PermanentFailure

    p = tmp_path / "t.jsonl"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with Telemetry(p) as tel:
            with pytest.raises(PermanentFailure):
                run_supervised(
                    HeatConfig(steps=100, cx=5.0, cy=5.0, **_BASE),
                    tmp_path / "ck",
                    policy=SupervisorPolicy(checkpoint_every=20,
                                            guard_interval=10,
                                            backoff_base_s=0.0),
                    telemetry=tel)
    ev = _events(p)
    pf = next(e for e in ev if e["event"] == "permanent_failure")
    assert "stability bound" in pf["diagnosis"]
    assert ev[-1]["event"] == "run_end"
    assert ev[-1]["outcome"] == "permanent_failure"


def test_supervised_interrupt_emits_signal_and_run_end(tmp_path):
    import signal

    p = tmp_path / "t.jsonl"
    with Telemetry(p) as tel:
        sres = run_supervised(
            HeatConfig(steps=100, **_BASE), tmp_path / "ck",
            policy=SupervisorPolicy(checkpoint_every=20,
                                    backoff_base_s=0.0),
            faults=FaultPlan(signal_at_chunk=2,
                             signum=int(signal.SIGTERM)),
            telemetry=tel)
    assert sres.interrupted
    ev = _events(p)
    sig = next(e for e in ev if e["event"] == "signal")
    assert sig["name"] == "SIGTERM"
    assert ev[-1]["event"] == "run_end"
    assert ev[-1]["outcome"] == "interrupted"


def test_telemetry_does_not_change_compiled_programs(tmp_path):
    # The acceptance contract: telemetry/annotation-enabled runs share
    # (and are bitwise identical to) un-instrumented executables — the
    # same regression the guard pins, extended to the telemetry layer
    # AND the diagnostics layer AND the pipelined dispatch loop AND
    # the heattrace plumbing: the fully-instrumented runs below add a
    # diag_interval on top of the sink (one at pipeline_depth=1 with a
    # trace context + job_id stamped on every envelope, one at
    # pipeline_depth=2) and must still hit only the plain run's cached
    # runners.
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.utils.tracing import TraceContext

    cfg = HeatConfig(steps=30, **_BASE)
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=10)]
    misses_before = solver._build_runner.cache_info().misses
    with Telemetry(tmp_path / "t.jsonl",
                   heartbeat=tmp_path / "hb.json",
                   trace=TraceContext("tT", "sT", "pT"),
                   job_id="jT") as tel:
        instr = [r.to_numpy()
                 for r in solve_stream(cfg.replace(diag_interval=10),
                                       chunk_steps=10,
                                       telemetry=tel,
                                       pipeline_depth=1)]
    with Telemetry(tmp_path / "p.jsonl", async_io=True) as tel:
        piped = [r.to_numpy()
                 for r in solve_stream(
                     cfg.replace(diag_interval=10, pipeline_depth=2),
                     chunk_steps=10, telemetry=tel)]
    assert solver._build_runner.cache_info().misses == misses_before
    for a, b, c in zip(plain, instr, piped):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    # and the diagnostics events actually landed (the contract is not
    # vacuous: instrumentation ran, programs still shared) — from BOTH
    # instrumented runs, at the same boundaries
    for name in ("t.jsonl", "p.jsonl"):
        diags = [e for e in _events(tmp_path / name)
                 if e["event"] == "diagnostics"]
        assert [d["step"] for d in diags] == [10, 20, 30]
    # and the trace triple actually rode the traced sink's envelope
    # (the contract is not vacuous for the heattrace layer either)
    traced = _events(tmp_path / "t.jsonl")
    assert all(e["trace_id"] == "tT" and e["span_id"] == "sT"
               and e["parent_span_id"] == "pT" and e["job_id"] == "jT"
               for e in traced)


def test_envelope_hostname_and_optional_trace_fields(tmp_path):
    import socket

    # hostname rides every envelope (schema 2: fleet joins and
    # straggler attribution need the host); job_id/trace only when set
    with Telemetry(tmp_path / "a.jsonl") as tel:
        tel.emit("chunk", step=1)
    ev = _events(tmp_path / "a.jsonl")
    assert ev[0]["schema"] == SCHEMA_VERSION == 2
    assert ev[0]["hostname"] == socket.gethostname()
    assert "job_id" not in ev[0] and "trace_id" not in ev[0]


def test_trace_context_inherited_from_environment(tmp_path, monkeypatch):
    # The daemon->worker inheritance path: a sink built with no
    # explicit context picks the HEATTRACE_* variables up, so a
    # spawned worker's stream joins the submit's trace with no flag.
    from parallel_heat_tpu.utils import tracing

    monkeypatch.setenv(tracing.ENV_TRACE_ID, "tE")
    monkeypatch.setenv(tracing.ENV_SPAN_ID, "sE")
    monkeypatch.setenv(tracing.ENV_PARENT_SPAN_ID, "pE")
    with Telemetry(tmp_path / "e.jsonl") as tel:
        tel.emit("chunk", step=1)
    ev = _events(tmp_path / "e.jsonl")
    assert ev[0]["trace_id"] == "tE"
    assert ev[0]["span_id"] == "sE"
    assert ev[0]["parent_span_id"] == "pE"
    # an explicit context wins over the environment
    with Telemetry(tmp_path / "x.jsonl",
                   trace=tracing.TraceContext("tX", "sX")) as tel:
        tel.emit("chunk", step=1)
    assert _events(tmp_path / "x.jsonl")[0]["trace_id"] == "tX"


def test_telemetry_survives_unwritable_sink(tmp_path):
    # Observation must never kill the run: a sink whose stream dies
    # mid-run warns once, goes quiet, and the simulation completes.
    p = tmp_path / "t.jsonl"
    tel = Telemetry(p)
    tel._f.close()  # simulate the disk yanking the stream mid-run
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        results = list(solve_stream(HeatConfig(steps=20, **_BASE),
                                    chunk_steps=10, telemetry=tel))
    assert len(results) == 2 and results[-1].steps_run == 20
    assert sum("telemetry sink" in str(x.message) for x in w) == 1
    tel.emit("chunk")  # dead sink: silent no-op, no second warning
    tel.close()


def test_run_header_idempotent_and_append_mode(tmp_path):
    p = tmp_path / "t.jsonl"
    cfg = HeatConfig(steps=10, **_BASE)
    with Telemetry(p) as tel:
        tel.run_header(cfg)
        tel.run_header(cfg)
    # a NEW sink on the same path appends (resume semantics): a second
    # segment gets its own header
    with Telemetry(p) as tel:
        tel.run_header(cfg)
    ev = _events(p)
    assert [e["event"] for e in ev] == ["run_header", "run_header"]


def test_cli_metrics_and_heartbeat_unsupervised(tmp_path):
    from parallel_heat_tpu.cli import main
    from parallel_heat_tpu.utils.io import read_dat

    m = tmp_path / "m.jsonl"
    out = tmp_path / "out.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "20",
                 "--backend", "jnp", "--metrics", str(m),
                 "--heartbeat", str(tmp_path / "hb.json"),
                 "--out", str(out), "--quiet"]) == 0
    ev = _events(m)
    assert [e["event"] for e in ev] == ["run_header", "chunk",
                                        "profile", "run_end"]
    assert ev[1]["step"] == 20
    assert ev[3]["outcome"] == "complete"
    assert (tmp_path / "hb.json").exists()
    # the metrics path is bitwise the plain path (one-chunk stream runs
    # the same compiled program)
    direct = tmp_path / "direct.dat"
    assert main(["--nx", "16", "--ny", "16", "--steps", "20",
                 "--backend", "jnp", "--out", str(direct),
                 "--quiet"]) == 0
    np.testing.assert_array_equal(read_dat(out), read_dat(direct))


def test_cli_resumed_segment_chunks_are_absolute(tmp_path):
    # A resumed unsupervised run appends to the same JSONL; its chunk
    # events must continue the first segment's ABSOLUTE numbering, not
    # restart from the segment-relative count.
    from parallel_heat_tpu.cli import main

    m = tmp_path / "m.jsonl"
    ck = tmp_path / "ck.npz"
    assert main(["--nx", "16", "--ny", "16", "--steps", "40",
                 "--backend", "jnp", "--checkpoint", str(ck),
                 "--checkpoint-every", "20", "--metrics", str(m),
                 "--quiet"]) == 0
    assert main(["--nx", "16", "--ny", "16", "--steps", "60",
                 "--backend", "jnp", "--resume", str(ck),
                 "--checkpoint", str(ck), "--checkpoint-every", "20",
                 "--metrics", str(m), "--quiet"]) == 0
    ev = _events(m)
    assert sum(1 for e in ev if e["event"] == "run_header") == 2
    assert [e["step"] for e in ev if e["event"] == "chunk"] \
        == [20, 40, 60]
    saves = [e["step"] for e in ev if e["event"] == "checkpoint_save"]
    assert saves == [20, 40, 60]
    assert [e["steps_done"] for e in ev if e["event"] == "run_end"] \
        == [40, 60]


def test_cli_metrics_flag_rides_resume_command(tmp_path):
    import signal

    with Telemetry(tmp_path / "t.jsonl") as tel:
        sres = run_supervised(
            HeatConfig(steps=100, **_BASE), tmp_path / "ck",
            policy=SupervisorPolicy(checkpoint_every=20,
                                    backoff_base_s=0.0),
            faults=FaultPlan(signal_at_chunk=2,
                             signum=int(signal.SIGTERM)),
            resume_extra_flags=("--metrics", str(tmp_path / "t.jsonl")),
            telemetry=tel)
    assert "--metrics" in sres.resume_command
