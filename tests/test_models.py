import jax.numpy as jnp
import numpy as np

import oracle
from parallel_heat_tpu.models import HeatPlate2D, HeatPlate3D


def test_init_matches_reference_formula():
    m = HeatPlate2D(20, 20)
    got = m.init_grid_np(np.float32)
    want = oracle.init_grid(20, 20, np.float32)
    np.testing.assert_array_equal(got, want)


def test_init_boundary_is_zero():
    m = HeatPlate2D(13, 9)
    u = m.init_grid_np()
    assert np.all(u[0, :] == 0) and np.all(u[-1, :] == 0)
    assert np.all(u[:, 0] == 0) and np.all(u[:, -1] == 0)


def test_device_init_matches_numpy_init():
    m = HeatPlate2D(32, 24)
    np.testing.assert_allclose(
        np.asarray(m.init_grid(jnp.float32)), m.init_grid_np(np.float32),
        rtol=1e-6,
    )


def test_block_init_assembles_to_global():
    m = HeatPlate2D(24, 16)
    full = m.init_grid_np(np.float32)
    bx, by = 12, 4
    for bi in range(2):
        for bj in range(4):
            blk = np.asarray(m.init_block((bx, by), (bi, bj)))
            np.testing.assert_allclose(
                blk, full[bi * bx:(bi + 1) * bx, bj * by:(bj + 1) * by],
                rtol=1e-6,
            )


def test_3d_init_separable_and_zero_boundary():
    m = HeatPlate3D(6, 7, 8)
    u = m.init_grid_np()
    assert u.shape == (6, 7, 8)
    assert np.all(u[0] == 0) and np.all(u[-1] == 0)
    assert np.all(u[:, 0, :] == 0) and np.all(u[:, :, -1] == 0)
    # spot value
    assert u[2, 3, 4] == 2 * 3 * 3 * 3 * 4 * 3
