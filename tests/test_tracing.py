"""heattrace: trace contexts, the span model, the Chrome trace
export, and the SLO gate (SEMANTICS.md extends the observation-only
contract to tracing — the plumbing observes existing artifacts and
never changes a run).

Fast cells run on synthetic event streams shaped exactly like the
writers' output (envelope schema 2). The heavy cells — a real 2-rank
thread-simulated supervised run with a split-brain fault (the
per-rank streams behind the ``chaos_r15_dryrun.json`` artifact) — are
marked ``slow`` (tier-1 already runs near its wall budget); CI's
``make trace-smoke`` covers the subprocess path end to end.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from parallel_heat_tpu.utils import tracing
from parallel_heat_tpu.utils.tracing import (
    TraceContext,
    chrome_trace,
    dispatch_span_id,
    link_streams_to_journal,
    new_trace_id,
    spans_from_journal,
    spans_from_stream,
    submit_span_id,
    worker_span_id,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_HEATTRACE = os.path.join(_ROOT, "tools", "heattrace.py")
_SLO_GATE = os.path.join(_ROOT, "tools", "slo_gate.py")


# ---------------------------------------------------------------------------
# TraceContext
# ---------------------------------------------------------------------------

def test_trace_context_dict_round_trip():
    ctx = TraceContext("t1", "s1", "p1")
    assert TraceContext.from_dict(ctx.to_dict()) == ctx
    root = TraceContext("t1", "s1")
    d = root.to_dict()
    assert "parent_span_id" not in d
    assert TraceContext.from_dict(d) == root
    # malformed inputs are None, never a crash (older specs/envelopes)
    assert TraceContext.from_dict(None) is None
    assert TraceContext.from_dict({"trace_id": "t"}) is None
    assert TraceContext.from_dict({"trace_id": 3, "span_id": "s"}) \
        is None


def test_trace_context_env_round_trip():
    ctx = TraceContext("t1", "s1", "p1")
    env = ctx.to_env()
    assert TraceContext.from_env(env) == ctx
    assert TraceContext.from_env({}) is None
    # a child hop: same trace, parent = the old span
    child = ctx.child("s2")
    assert child.trace_id == "t1" and child.parent_span_id == "s1"


def test_deterministic_span_ids_and_trace_id_entropy():
    assert submit_span_id("j1") == "s-submit-j1"
    assert dispatch_span_id("j1", 2) == "s-dispatch-j1-a002"
    assert worker_span_id("j1", 2) == "s-worker-j1-a002"
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64  # collision-free without randomness


# ---------------------------------------------------------------------------
# Synthetic streams (envelope schema 2, the writers' exact shape)
# ---------------------------------------------------------------------------

def _env(event, t_mono, rank=0, trace=None, job_id=None, **fields):
    rec = {"schema": 2, "event": event, "t_wall": 1000.0 + t_mono,
           "t_mono": t_mono, "process_index": rank,
           "process_count": 2 if rank else 1, "hostname": f"host{rank}"}
    if trace is not None:
        rec.update(trace.to_dict())
    if job_id is not None:
        rec["job_id"] = job_id
    rec.update(fields)
    return rec


def _rank_events(rank, trace=None, job_id=None):
    """One rank's telemetry for a supervised run with a rollback —
    the event families the chaos cells certify, in their real order."""
    ev = [
        _env("run_header", 10.0, rank, trace, job_id,
             config={"nx": 16, "ny": 16, "steps": 60},
             steps_total=60),
        _env("checkpoint_save", 10.5, rank, trace, job_id, step=0,
             wall_s=0.2, generation=1),
        _env("chunk", 11.0, rank, trace, job_id, step=20, steps=20,
             wall_s=0.4, cells=256, bytes_per_cell=8),
        _env("barrier_wait", 11.05, rank, trace, job_id, step=20,
             wait_s=0.01 + 0.04 * rank),
        _env("guard_trip", 11.2, rank, trace, job_id, step=40,
             window=[20, 40]),
        _env("retry", 11.3, rank, trace, job_id, retry=1,
             max_retries=3, kind="guard trip", backoff_s=0.0),
        _env("rollback", 11.6, rank, trace, job_id, step=20,
             path="/ck/g20", load_wall_s=0.1),
        _env("chunk", 12.2, rank, trace, job_id, step=40, steps=20,
             wall_s=0.4, cells=256, bytes_per_cell=8),
        _env("barrier_wait", 12.25, rank, trace, job_id, step=40,
             wait_s=0.01 + 0.04 * rank),
        _env("chunk", 12.8, rank, trace, job_id, step=60, steps=20,
             wall_s=0.4, cells=256, bytes_per_cell=8),
        _env("checkpoint_barrier", 12.9, rank, trace, job_id,
             reason="final", wait_s=0.02),
        _env("run_end", 13.0, rank, trace, job_id, outcome="complete",
             steps_done=60),
    ]
    return ev


def _by_id(spans):
    return {s["span_id"]: s for s in spans}


def test_stream_spans_single_rank_structure():
    trace = TraceContext("tX", "s-worker-j1-a001", "s-dispatch-j1-a001")
    spans, instants = spans_from_stream(
        _rank_events(0, trace, job_id="j1"))
    ids = _by_id(spans)
    # the synthetic worker span IS the envelope span (the causal hop
    # below the journal's dispatch span)
    worker = ids["s-worker-j1-a001"]
    assert worker["parent_span_id"] == "s-dispatch-j1-a001"
    assert worker["args"]["job_id"] == "j1"
    runs = [s for s in spans if s["cat"] == "run"]
    assert len(runs) == 1
    assert runs[0]["parent_span_id"] == "s-worker-j1-a001"
    chunks = [s for s in spans if s["cat"] == "chunk"]
    assert [c["args"]["step"] for c in chunks] == [20, 40, 60]
    for c in chunks:
        # queue->worker->chunk parentage + interval nesting inside
        # the run segment
        assert c["parent_span_id"] == runs[0]["span_id"]
        assert runs[0]["t0"] <= c["t0"] <= c["t1"] <= runs[0]["t1"]
        assert c["t1"] - c["t0"] == pytest.approx(0.4)
    # every span resolves upward within the trace
    for s in spans:
        par = s["parent_span_id"]
        assert par is None or par in ids \
            or par == "s-dispatch-j1-a001"
        assert s["trace_id"] == "tX"
    # rollback load + the replay segment span
    cats = {s["cat"] for s in spans}
    assert {"rollback", "consensus", "checkpoint"} <= cats
    # lifecycle instants (guard_trip/retry) are marks, not spans
    assert {i["name"] for i in instants} >= {"guard_trip", "retry"}
    # t_mono anchored at run_header: wall-aligned absolute times
    assert runs[0]["t0"] == pytest.approx(1010.0)


def test_stream_spans_two_ranks_merge_onto_one_timeline():
    trace = TraceContext("tX", "s-worker-j1-a001", "s-dispatch-j1-a001")
    merged = _rank_events(0, trace, "j1") + _rank_events(1, trace, "j1")
    spans, _ = spans_from_stream(merged)
    runs = {s["args"]["process_index"]: s for s in spans
            if s["cat"] == "run"}
    assert set(runs) == {0, 1}
    # rank lanes are distinct, times share one wall-aligned axis
    assert runs[0]["tid"] != runs[1]["tid"]
    assert runs[0]["t0"] == pytest.approx(runs[1]["t0"])
    # per-rank barrier_wait spans carry each rank's own wait
    waits = sorted((s["tid"], round(s["t1"] - s["t0"], 3))
                   for s in spans if s["cat"] == "consensus")
    assert waits == [("rank 0", 0.01), ("rank 0", 0.01),
                     ("rank 1", 0.05), ("rank 1", 0.05)]
    # chunk parentage holds on BOTH ranks
    for s in spans:
        if s["cat"] == "chunk":
            rank = int(s["tid"].split()[1])
            assert s["parent_span_id"] == runs[rank]["span_id"]


def test_stream_spans_untraced_and_foreign_lines_degrade():
    ev = _rank_events(0)  # no trace context, no job_id
    ev.insert(3, {"foreign": "line"})  # shaped wrong
    ev.insert(5, {"event": "chunk"})  # no timestamps at all
    spans, _ = spans_from_stream(ev)
    assert any(s["cat"] == "chunk" for s in spans)
    assert all(s["trace_id"] == "untraced" for s in spans)


def test_ensemble_member_lanes():
    tr = TraceContext("tP", "s-worker-p1-a001")
    ev = [
        _env("pack_header", 5.0, 0, tr, "p1", pack="p1", members=2,
             job_ids=["p1", "p2"]),
        _env("run_header", 5.1, 0, tr, "p1",
             config={"nx": 16}, steps_total=60),
        _env("member_converged", 6.0, 0, tr, "p1", member=1, step=40,
             residual=1e-4),
        _env("member_end", 6.5, 0, tr, "p1", member=0, step=60,
             converged=False, residual=2e-3),
        _env("member_end", 6.5, 0, tr, "p1", member=1, step=40,
             converged=True, residual=1e-4),
        _env("run_end", 6.6, 0, tr, "p1", outcome="complete"),
    ]
    spans, instants = spans_from_stream(ev)
    members = [s for s in spans if s["cat"] == "member"]
    assert {s["tid"] for s in members} \
        == {"rank 0 member 0", "rank 0 member 1"}
    conv = next(i for i in instants if i["name"] == "member_converged")
    assert conv["tid"] == "rank 0 member 1"


# ---------------------------------------------------------------------------
# Journal spans
# ---------------------------------------------------------------------------

def _journal(jid="j1", trace_id="tX", requeue=True):
    ev = [{"event": "accepted", "job_id": jid, "t_wall": 100.0,
           "trace_id": trace_id},
          {"event": "dispatched", "job_id": jid, "t_wall": 101.5,
           "worker": f"w-{jid}-a001", "attempt": 1,
           "trace_id": trace_id}]
    if requeue:
        ev += [{"event": "orphaned", "job_id": jid, "t_wall": 103.0,
                "worker": f"w-{jid}-a001", "attempt": 1},
               {"event": "requeued", "job_id": jid, "t_wall": 103.0,
                "not_before": 103.5, "reason": "orphaned"},
               {"event": "dispatched", "job_id": jid, "t_wall": 104.0,
                "worker": f"w-{jid}-a002", "attempt": 2,
                "trace_id": trace_id}]
    ev.append({"event": "completed", "job_id": jid, "t_wall": 106.0,
               "attempt": 2 if requeue else 1, "steps_done": 60})
    return ev


def test_journal_spans_queue_wait_and_attempts():
    spans, instants = spans_from_journal(_journal())
    ids = _by_id(spans)
    job = ids[submit_span_id("j1")]
    assert job["t0"] == 100.0 and job["t1"] == 106.0
    assert job["trace_id"] == "tX"
    waits = [s for s in spans if s["name"] == "queue wait"]
    # accepted->dispatch AND requeued->re-dispatch both count: the
    # queue-wait SLO is about every wait, not just the first
    assert [round(s["t1"] - s["t0"], 3) for s in waits] == [1.5, 0.5]
    atts = [s for s in spans if s["cat"] == "dispatch"]
    assert [s["span_id"] for s in atts] \
        == [dispatch_span_id("j1", 1), dispatch_span_id("j1", 2)]
    for s in waits + atts:
        assert s["parent_span_id"] == job["span_id"]
        assert job["t0"] <= s["t0"] <= s["t1"] <= job["t1"]
    assert {i["name"] for i in instants} \
        >= {"orphaned", "requeued", "completed"}


def test_link_streams_to_journal_by_deterministic_ids():
    jspans, _ = spans_from_journal(_journal(requeue=False))
    # an UNTRACED stream (older writer): linked by job_id + attempt
    sspans, _ = spans_from_stream(_rank_events(0, job_id="j1"))
    n = link_streams_to_journal(sspans, jspans)
    assert n == 1
    worker = next(s for s in sspans if s["cat"] == "worker")
    assert worker["parent_span_id"] == dispatch_span_id("j1", 1)


# ---------------------------------------------------------------------------
# Chrome export
# ---------------------------------------------------------------------------

def _validate_chrome(doc):
    """The Chrome trace-event contract the export must satisfy."""
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "i", "M", "C")
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] in ("X", "i", "C"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
            assert "span_id" in e["args"]
    # span ids are unique; every parent resolves or is explicitly
    # outside the document (an env-inherited dispatch parent)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ids = [e["args"]["span_id"] for e in xs]
    assert len(ids) == len(set(ids))
    return xs


def test_chrome_trace_round_trip_and_nesting():
    trace = TraceContext("tX", "s-worker-j1-a001", "s-dispatch-j1-a001")
    spans, instants = spans_from_stream(
        _rank_events(0, trace, "j1") + _rank_events(1, trace, "j1"))
    jspans, jinst = spans_from_journal(_journal(requeue=False))
    link_streams_to_journal(spans, jspans)
    doc = chrome_trace(jspans + spans, jinst + instants)
    doc = json.loads(json.dumps(doc))  # byte-level JSON validity
    xs = _validate_chrome(doc)
    by_id = {e["args"]["span_id"]: e for e in xs}
    # the full causal chain: submit -> dispatch -> worker -> run ->
    # chunk, across BOTH ranks
    chunk_parents = set()
    for e in xs:
        if e["name"].startswith("chunk"):
            run = by_id[e["args"]["parent_span_id"]]
            worker = by_id[run["args"]["parent_span_id"]]
            dispatch = by_id[worker["args"]["parent_span_id"]]
            job = by_id[dispatch["args"]["parent_span_id"]]
            assert job["args"]["span_id"] == submit_span_id("j1")
            chunk_parents.add(run["args"]["span_id"])
    assert len(chunk_parents) == 2  # one run lane per rank


# ---------------------------------------------------------------------------
# CLI round trips (subprocess: the tools must not rot)
# ---------------------------------------------------------------------------

def _write_stream(path, events, torn=False, garbage=False):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        if garbage:
            f.write("not json at all\n")
        if torn:
            f.write('{"event": "chunk", "t_')  # mid-append tear


def test_heattrace_cli_round_trip(tmp_path):
    trace = TraceContext("tX", "s-worker-j1-a001", "s-dispatch-j1-a001")
    _write_stream(tmp_path / "m.p0.jsonl", _rank_events(0, trace, "j1"))
    _write_stream(tmp_path / "m.p1.jsonl", _rank_events(1, trace, "j1"),
                  torn=True, garbage=True)
    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, _HEATTRACE, str(tmp_path / "m.p*.jsonl"),
         "--out", str(out), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout)
    assert len(summary["streams"]) == 2
    assert summary["streams"][1]["torn_tail"] is True
    doc = json.load(open(out))
    xs = _validate_chrome(doc)
    assert sum(1 for e in xs if e["name"].startswith("chunk")) == 6
    # thread lanes name both ranks
    names = {e["args"]["name"]
             for e in doc["traceEvents"] if e["ph"] == "M"}
    assert {"rank 0", "rank 1"} <= names


def test_heattrace_cli_unusable_input(tmp_path):
    empty = tmp_path / "nothing.jsonl"
    empty.write_text("")
    r = subprocess.run(
        [sys.executable, _HEATTRACE, str(empty),
         "--out", str(tmp_path / "t.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "no spans derivable" in r.stderr


# ---------------------------------------------------------------------------
# slo_gate
# ---------------------------------------------------------------------------

def _busy_chunk(t, step, rank=0, gap=0.01):
    return _env("chunk", t, rank, step=step, steps=20, wall_s=0.4,
                cells=256, bytes_per_cell=8, gap_s=gap,
                observe_s=0.002)


def _slo(tmp_path, spec):
    p = tmp_path / "slo.json"
    p.write_text(json.dumps(spec))
    return str(p)


def test_slo_gate_stream_clean_and_violated(tmp_path):
    ev = [_env("run_header", 1.0, config={"nx": 16}, steps_total=60),
          _busy_chunk(2.0, 20), _busy_chunk(3.0, 40),
          _busy_chunk(4.0, 60),
          _env("run_end", 5.0, outcome="complete", steps_done=60)]
    _write_stream(tmp_path / "m.jsonl", ev)
    spec = _slo(tmp_path, {"stream": ["permanent_failure",
                                      "busy<0.5"]})
    clean = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "m.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stderr[-2000:]
    assert "all SLOs held" in clean.stdout
    # doctor the artifact: a permanent_failure event + an idle device
    bad = ev[:-1] + [
        _busy_chunk(6.0, 80, gap=9.0),
        _env("permanent_failure", 7.0, diagnosis="doctored",
             kind="exhausted"),
        _env("run_end", 8.0, outcome="permanent_failure")]
    _write_stream(tmp_path / "bad.jsonl", bad)
    v = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "bad.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert v.returncode == 2
    assert "permanent_failure" in v.stdout
    assert "device-busy fraction" in v.stdout


def test_slo_gate_barrier_wait_straggler_attribution(tmp_path):
    # rank 1 waits long at every consensus boundary; rank 0 never
    # does — rank 0 is the dominant straggler (the one rank 1 waits
    # FOR), and the violation must say so by rank and host.
    def shard(rank, wait):
        return ([_env("run_header", 1.0, rank, config={"nx": 16})]
                + [_env("barrier_wait", 2.0 + i, rank, step=20 * i,
                        wait_s=wait) for i in range(5)]
                + [_env("run_end", 9.0, rank, outcome="complete")])

    _write_stream(tmp_path / "m.p0.jsonl", shard(0, 0.001))
    _write_stream(tmp_path / "m.p1.jsonl", shard(1, 0.8))
    spec = _slo(tmp_path, {"stream": ["barrier_wait_p99>0.5"]})
    r = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "m.p*.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    line = next(ln for ln in r.stdout.splitlines()
                if "barrier-wait p99" in ln)
    assert "rank 1 on host1" in line  # the violating rank
    assert "dominant straggler: rank 0 on host0" in line


def test_slo_gate_fleet_root_and_heartbeat_freshness(tmp_path):
    from parallel_heat_tpu.service.store import JobStore

    root = tmp_path / "q"
    store = JobStore(str(root))
    j = store.journal
    j.append("accepted", job_id="j1", trace_id="tX")
    j.append("dispatched", job_id="j1", worker="w1", attempt=1)
    j.append("completed", job_id="j1", attempt=1, steps_done=60)
    store.write_daemon_status({"pid": 1, "t_wall": 1000.0,
                               "state": "serving", "slots": 2})
    store.close()
    spec = _slo(tmp_path, {"fleet": ["quarantined>0", "orphaned>0",
                                     "queue_wait_s.p99>30"],
                           "heartbeat_max_age_s": 60})
    clean = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec, str(root),
         "--now", "1010"],
        capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stderr[-2000:]
    # a stale heartbeat while claiming to serve violates freshness
    stale = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec, str(root),
         "--now", "5000"],
        capture_output=True, text=True, timeout=120)
    assert stale.returncode == 2 and "heartbeat" in stale.stdout
    # doctor the journal: a quarantined job trips the fleet SLO
    store2 = JobStore(str(root))
    store2.journal.append("accepted", job_id="j2")
    store2.journal.append("quarantined", job_id="j2", kind="unstable")
    store2.close()
    v = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec, str(root),
         "--now", "1010"],
        capture_output=True, text=True, timeout=120)
    assert v.returncode == 2 and "quarantined" in v.stdout


def test_slo_gate_empty_gate_is_an_error(tmp_path):
    _write_stream(tmp_path / "m.jsonl",
                  [_env("run_header", 1.0, config={})])
    r = subprocess.run(
        [sys.executable, _SLO_GATE, str(tmp_path / "m.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "gates nothing" in r.stderr


# ---------------------------------------------------------------------------
# End-to-end: the real service path (inline worker), then the chaos
# artifact path (2 real thread-simulated ranks) — the latter slow.
# ---------------------------------------------------------------------------

def test_trace_context_threads_queue_to_telemetry(tmp_path):
    # client.submit births the trace; the spec commits it; the daemon
    # journals it; the inline worker (no env crossing) falls back to
    # the spec and stamps the envelope: the WHOLE chain is joined by
    # ids, no path conventions.
    from parallel_heat_tpu.service import client
    from parallel_heat_tpu.service import worker as svc_worker
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig

    root = str(tmp_path / "q")

    class InlineHandle:
        def __init__(self, run):
            self._run = run
            self._rc = None
            self.pid = os.getpid()

        def poll(self):
            if self._rc is None:
                self._rc = self._run()
            return self._rc

        def terminate(self):
            pass

        kill = terminate

    def launcher(job_id, worker_id, attempt, deadline_t):
        return InlineHandle(lambda: svc_worker.execute_job(
            root, job_id, worker_id, attempt, deadline_t=deadline_t))

    d = Heatd(HeatdConfig(root=root, launcher=launcher,
                          worker_heartbeat_s=0.05,
                          heartbeat_timeout_s=10.0))
    t = {"now": 0.0}

    def sleep(s):
        t["now"] += s
        d.step()

    verdict = client.submit(root, {"nx": 16, "ny": 16, "steps": 40,
                                   "backend": "jnp"},
                            job_id="jt", accept_timeout_s=60.0,
                            clock=lambda: t["now"], sleep_fn=sleep)
    assert verdict["accepted"] and verdict["trace_id"]
    tid = verdict["trace_id"]
    for _ in range(6):
        d.step()
        jobs, _ = d.store.replay()
        if jobs["jt"].terminal:
            break
    jobs, anomalies = d.store.replay()
    assert anomalies == [] and jobs["jt"].state == "completed"
    # the reducer carries the trace id off the accepted line
    assert jobs["jt"].trace_id == tid
    # journal lines carry it raw too (heattrace reads them directly)
    events, _, _ = d.store.read_journal()
    for ev in ("accepted", "dispatched"):
        line = next(e for e in events if e.get("event") == ev
                    and e.get("job_id") == "jt")
        assert line["trace_id"] == tid
    # the worker's telemetry envelope joined the same trace, as a
    # child of the dispatch span, with job_id + hostname stamped
    with open(d.store.telemetry_path("jt")) as f:
        tev = [json.loads(ln) for ln in f if ln.strip()]
    hdr = next(e for e in tev if e["event"] == "run_header")
    assert hdr["trace_id"] == tid
    assert hdr["span_id"] == worker_span_id("jt", 1)
    assert hdr["parent_span_id"] == dispatch_span_id("jt", 1)
    assert hdr["job_id"] == "jt" and hdr["hostname"]
    d.store.close()

    # and heattrace renders the whole chain from the artifacts alone
    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, _HEATTRACE, "--queue", root,
         "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    xs = _validate_chrome(json.load(open(out)))
    by_id = {e["args"]["span_id"]: e for e in xs}
    chunk = next(e for e in xs if e["name"].startswith("chunk"))
    run = by_id[chunk["args"]["parent_span_id"]]
    worker = by_id[run["args"]["parent_span_id"]]
    dispatch = by_id[worker["args"]["parent_span_id"]]
    job = by_id[dispatch["args"]["parent_span_id"]]
    assert job["args"]["span_id"] == submit_span_id("jt")
    assert {e["args"]["trace_id"] for e in (chunk, run, worker,
                                            dispatch, job)} == {tid}


@pytest.mark.slow
@pytest.mark.chaos
def test_heattrace_on_two_rank_split_brain_artifact(tmp_path):
    # The chaos-artifact cell (the per-rank streams behind
    # chaos_r15_dryrun.json's mp rows): a REAL 2-rank thread-simulated
    # supervised run with a rank-1 NaN injection writes per-rank
    # telemetry shards; heattrace must merge both onto one timeline
    # with queue->worker->chunk->barrier parentage on BOTH ranks and
    # the rollback visible.
    from parallel_heat_tpu import (
        HeatConfig,
        SupervisorPolicy,
        Telemetry,
        run_supervised,
    )
    from parallel_heat_tpu.parallel.coordinator import (
        InMemoryKV,
        KVCoordinator,
    )
    from parallel_heat_tpu.utils.faults import FaultPlan

    kv = InMemoryKV()
    cfg = HeatConfig(nx=16, ny=16, steps=60, backend="jnp")
    trace = TraceContext("t2rank", worker_span_id("jmp", 1),
                         dispatch_span_id("jmp", 1))
    results = [None, None]
    errs = [None, None]

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=20.0,
                              heartbeat_interval_s=0.05)
        tel = Telemetry(str(tmp_path / "m.jsonl"), process_index=i,
                        process_count=2, trace=trace, job_id="jmp")
        try:
            results[i] = run_supervised(
                cfg, tmp_path / "ck",
                policy=SupervisorPolicy(checkpoint_every=20,
                                        guard_interval=10,
                                        backoff_base_s=0.0,
                                        barrier_timeout_s=20.0,
                                        async_checkpoint=False),
                faults=(FaultPlan(nan_at_step=35, only_process=1)
                        if i == 1 else None),
                telemetry=tel, coordinator=coord)
        except BaseException as e:  # noqa: BLE001
            errs[i] = e
        finally:
            tel.close()
            coord.close()

    threads = [threading.Thread(target=rank, args=(i,))
               for i in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=240)
    assert errs == [None, None]
    assert all(r.steps_done == 60 for r in results)
    assert all(r.rollbacks == 1 for r in results)

    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, _HEATTRACE, str(tmp_path / "m.p*.jsonl"),
         "--out", str(out), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    xs = _validate_chrome(json.load(open(out)))
    by_id = {e["args"]["span_id"]: e for e in xs}
    runs = [e for e in xs if e["name"].startswith("run segment")]
    assert len(runs) == 2  # one lane per rank
    # chunk->run->worker parentage on both ranks; barrier_wait spans
    # present per rank (the consensus exchanges of the mp cells)
    barrier_lanes, chunk_lanes = set(), set()
    for e in xs:
        if e["name"].startswith("barrier_wait"):
            barrier_lanes.add(e["tid"])
        if e["name"].startswith("chunk"):
            chunk_lanes.add(e["tid"])
            run = by_id[e["args"]["parent_span_id"]]
            assert by_id[run["args"]["parent_span_id"]]["args"][
                "span_id"] == worker_span_id("jmp", 1)
    assert len(barrier_lanes) == 2 and len(chunk_lanes) == 2
    # the split-brain rollback is on the timeline (both ranks rolled
    # back together — the consensus contract)
    assert sum(1 for e in xs
               if e["name"].startswith("rollback load")) == 2
    # both ranks' consensus_verdict instants agree on the action
    verdicts = [e for e in json.load(open(out))["traceEvents"]
                if e["ph"] == "i" and e["name"] == "consensus_verdict"]
    assert {v["args"]["action"] for v in verdicts} == {"nan"}

    # the doctored-vs-clean SLO verdict on the same artifact
    spec = tmp_path / "slo.json"
    spec.write_text(json.dumps(
        {"stream": ["permanent_failure", "barrier_wait_p99>30"]}))
    clean = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", str(spec),
         str(tmp_path / "m.p*.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stderr[-2000:]
    tight = tmp_path / "tight.json"
    tight.write_text(json.dumps(
        {"stream": ["barrier_wait_p99>0.0000001", "guard_trip"]}))
    v = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", str(tight),
         str(tmp_path / "m.p*.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert v.returncode == 2
    assert "dominant straggler" in v.stdout


def test_tracing_module_has_no_jax_dependency():
    # tracing must stay importable by jax-free consumers (the service
    # store/daemon import it at module scope).
    src = open(os.path.join(_ROOT, "parallel_heat_tpu", "utils",
                            "tracing.py")).read()
    assert "import jax" not in src


# ---------------------------------------------------------------------------
# Review regressions
# ---------------------------------------------------------------------------

def test_untraced_streams_from_different_runs_do_not_merge(tmp_path):
    # Regression (review finding): two UNTRACED runs (plain --metrics,
    # no trace context) exported together must keep their spans apart
    # — synthetic span ids seed off the stream key, so merge_spans can
    # never fuse unrelated runs into one garbled timeline.
    def run_events(t0):
        return ([_env("run_header", t0, config={"nx": 16},
                      steps_total=60)]
                + [_env("chunk", t0 + i, step=20 * i, steps=20,
                        wall_s=0.4, cells=256, bytes_per_cell=8)
                   for i in range(1, 4)]
                + [_env("run_end", t0 + 4, outcome="complete")])

    _write_stream(tmp_path / "runA.jsonl", run_events(10.0))
    _write_stream(tmp_path / "runB.jsonl", run_events(5000.0))
    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, _HEATTRACE, str(tmp_path / "runA.jsonl"),
         str(tmp_path / "runB.jsonl"), "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    xs = _validate_chrome(json.load(open(out)))
    chunks = [e for e in xs if e["name"].startswith("chunk")]
    assert len(chunks) == 6  # three per run, none fused
    # and no chunk span stretches across both runs' epochs
    assert all(e["dur"] < 10e6 for e in chunks)


def test_multi_attempt_stream_parents_each_attempt_correctly():
    # Regression (review finding): heatd appends every attempt to the
    # same per-job sink; attempt 2's envelopes carry their own span
    # context and must hang off attempt 2's dispatch span, never
    # attempt 1's.
    tr1 = TraceContext("tX", worker_span_id("j1", 1),
                       dispatch_span_id("j1", 1))
    tr2 = TraceContext("tX", worker_span_id("j1", 2),
                       dispatch_span_id("j1", 2))
    a1 = [_env("run_header", 10.0, 0, tr1, "j1",
               config={"nx": 16}, steps_total=60),
          _env("chunk", 11.0, 0, tr1, "j1", step=20, steps=20,
               wall_s=0.4, cells=256, bytes_per_cell=8)]
    a2 = [_env("run_header", 50.0, 0, tr2, "j1",
               config={"nx": 16}, steps_total=60),
          _env("chunk", 51.0, 0, tr2, "j1", step=40, steps=20,
               wall_s=0.4, cells=256, bytes_per_cell=8),
          _env("run_end", 52.0, 0, tr2, "j1", outcome="complete")]
    spans, _ = spans_from_stream(a1 + a2)
    ids = _by_id(spans)
    w1 = ids[worker_span_id("j1", 1)]
    w2 = ids[worker_span_id("j1", 2)]
    assert w1["parent_span_id"] == dispatch_span_id("j1", 1)
    assert w2["parent_span_id"] == dispatch_span_id("j1", 2)
    for s in spans:
        if s["cat"] == "chunk":
            run = ids[s["parent_span_id"]]
            expect = (worker_span_id("j1", 1)
                      if s["args"]["step"] == 20
                      else worker_span_id("j1", 2))
            assert run["parent_span_id"] == expect


def test_fleet_fail_on_tolerates_stream_floor_tokens(tmp_path):
    # Regression (review finding): one --fail-on string must stay
    # usable across modes — the documented stream default
    # 'permanent_failure,busy<0.95' on a queue root skips the floor
    # it cannot resolve instead of hard-erroring.
    from parallel_heat_tpu.service.store import JobStore

    root = tmp_path / "q"
    store = JobStore(str(root))
    store.journal.append("accepted", job_id="j1")
    store.journal.append("dispatched", job_id="j1", worker="w1",
                         attempt=1)
    store.journal.append("completed", job_id="j1", attempt=1)
    store.close()
    mr = os.path.join(_ROOT, "tools", "metrics_report.py")
    r = subprocess.run(
        [sys.executable, mr, str(root),
         "--fail-on", "permanent_failure,busy<0.95"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, (r.stdout, r.stderr[-1000:])


def test_slo_gate_gates_every_stream_in_a_glob(tmp_path):
    # Review regression: a glob over INDEPENDENT per-job sinks (the
    # trace-smoke / CI pattern) must gate every stream — a violation
    # in the second file must not hide behind the first file's
    # primary-shard aggregate. Shard families (.pN of one stem) still
    # gate as one run.
    clean = [_env("run_header", 1.0, config={"nx": 16}),
             _env("run_end", 2.0, outcome="complete")]
    bad = [_env("run_header", 1.0, config={"nx": 16}),
           _env("guard_trip", 1.5, step=20, window=[0, 20]),
           _env("permanent_failure", 2.0, diagnosis="doctored",
                kind="exhausted"),
           _env("run_end", 2.5, outcome="permanent_failure")]
    _write_stream(tmp_path / "job-a.jsonl", clean)
    _write_stream(tmp_path / "job-b.jsonl", bad)
    spec = _slo(tmp_path, {"stream": ["permanent_failure",
                                      "guard_trip"]})
    r = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "job-*.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 2, (r.stdout, r.stderr[-1000:])
    assert "job-b.jsonl" in r.stdout
    assert "permanent_failure" in r.stdout and "guard_trip" in r.stdout
    # an empty sink among live ones is skipped with a warning, not a
    # hard error; a target with NO gateable stream is unusable
    (tmp_path / "job-c.jsonl").write_text("")
    r2 = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "job-*.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 2 and "job-c" in r2.stderr
    r3 = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec,
         str(tmp_path / "job-c.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert r3.returncode == 1


def test_unmeasured_fleet_percentile_passes_misspelled_errors(tmp_path):
    # Review regression: a young queue (accepted, never dispatched)
    # has queue_wait_s.p99 = None — a threshold on it must PASS (it is
    # unmeasured, not violated, and certainly not a misspelled
    # counter), while a genuinely unknown name stays a loud error.
    from parallel_heat_tpu.service.store import JobStore

    root = tmp_path / "q"
    store = JobStore(str(root))
    store.journal.append("accepted", job_id="j1")
    store.close()
    spec = _slo(tmp_path, {"fleet": ["queue_wait_s.p99>60",
                                     "quarantined>0"]})
    r = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec", spec, str(root)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr[-1000:])
    mr = os.path.join(_ROOT, "tools", "metrics_report.py")
    r2 = subprocess.run(
        [sys.executable, mr, str(root),
         "--fail-on", "queue_wait_s.p99>60"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r2.returncode == 0, (r2.stdout, r2.stderr[-1000:])
    bad = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec",
         _slo(tmp_path, {"fleet": ["nonsense.p99>1"]}), str(root)],
        capture_output=True, text=True, timeout=120)
    assert bad.returncode == 1 and "not a fleet counter" in bad.stderr


def test_peer_lost_gates_only_when_spec_names_it(tmp_path):
    # Review regression: peer_lost is spec-driven like every other
    # event token (a fleet that intentionally rides the
    # elastic-degrade path must be able to pass), but evaluates per
    # shard when named — only survivors' shards carry it.
    survivors = [_env("run_header", 1.0, config={"nx": 16}),
                 _env("peer_lost", 2.0, step=20, lost=[1],
                      survivors=1, waited_s=3.0, timeout_s=3.0),
                 _env("run_end", 2.5, outcome="interrupted")]
    _write_stream(tmp_path / "m.jsonl", survivors)
    without = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec",
         _slo(tmp_path, {"stream": ["permanent_failure"]}),
         str(tmp_path / "m.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert without.returncode == 0, without.stdout
    named = subprocess.run(
        [sys.executable, _SLO_GATE, "--spec",
         _slo(tmp_path, {"stream": ["peer_lost"]}),
         str(tmp_path / "m.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert named.returncode == 2 and "PEER_LOST" in named.stdout


def test_spawn_worker_clears_stale_trace_env(tmp_path, monkeypatch):
    # Review regression: a daemon started from a traced environment
    # must not leak foreign HEATTRACE_* variables into an UNTRACED
    # job's worker (its stream would join an unrelated causal chain).
    from parallel_heat_tpu.service import daemon as svc_daemon
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig

    captured = {}

    class _P:
        pid = 1

        def __init__(self, argv, **kw):
            captured["env"] = kw["env"]

        def poll(self):
            return 0

    monkeypatch.setattr(svc_daemon.subprocess, "Popen", _P)
    monkeypatch.setenv(tracing.ENV_TRACE_ID, "stale-trace")
    monkeypatch.setenv(tracing.ENV_SPAN_ID, "stale-span")
    d = Heatd(HeatdConfig(root=str(tmp_path / "q")))
    d._spawn_worker(["--job", "x"], "w-x")
    assert tracing.ENV_TRACE_ID not in captured["env"]
    assert tracing.ENV_SPAN_ID not in captured["env"]
    d._spawn_worker(["--job", "x"], "w-x",
                    trace=TraceContext("tF", "sF"))
    assert captured["env"][tracing.ENV_TRACE_ID] == "tF"
    assert captured["env"][tracing.ENV_SPAN_ID] == "sF"
    d.store.close()
