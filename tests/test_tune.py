"""Measured autotuning (SEMANTICS.md "Tuning soundness"): the tuning
DB's journal discipline — fold law, torn tails, both crash windows —
mirrored from tests/test_cache.py; the loud-fallback contract on
doctored/unverified evidence; the bitwise parity sweep over every
DB-selectable single-grid schedule; and the HL101 partition (toggling
the DB never perturbs the runner cache).
"""

import json
import os

import numpy as np
import pytest

from parallel_heat_tpu import tune
from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.tune import db as T

# ---------------------------------------------------------------------------
# Isolation: the active DB is process-global orchestration state; every
# test starts with tuning OFF and leaves no DB behind.
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _tuning_off(monkeypatch):
    monkeypatch.delenv("PHT_TUNE_DB", raising=False)
    prev = tune._active_db
    tune._active_db = None
    yield
    cur = tune._active_db
    if cur not in (None, tune._ACTIVE_SENTINEL):
        cur.close()
    tune._active_db = prev


_TOPO = {"platform": "cpu", "device_kind": "tpu_v4", "n_devices": 1}
_GEOM = {"shape": [64, 64], "dtype": "float32", "accumulate": "storage"}


def _put(key, t=1.0, **kw):
    e = {"event": "tune_put", "key": key,
         "db_schema": T.TUNE_SCHEMA_VERSION, "site": "single_2d",
         "topology": _TOPO, "geometry": _GEOM, "choice": "E",
         "detail": None, "verified": True, "n_candidates": 4,
         "record": f"{key}.json", "t_wall": t}
    e.update(kw)
    return e


# ---------------------------------------------------------------------------
# Content-addressed keys
# ---------------------------------------------------------------------------

def test_tune_key_content_address():
    k1, canon = T.tune_key("single_2d", _TOPO, _GEOM)
    k2, _ = T.tune_key("single_2d", dict(_TOPO), dict(_GEOM))
    assert k1 == k2 and len(k1) == 40
    assert canon["schema"] == T.TUNE_SCHEMA_VERSION
    # Any coordinate flip moves the key — entries can never shadow a
    # different site, topology, or geometry.
    assert T.tune_key("ensemble_2d", _TOPO, _GEOM)[0] != k1
    assert T.tune_key("single_2d", {**_TOPO, "n_devices": 8},
                      _GEOM)[0] != k1
    assert T.tune_key("single_2d", _TOPO,
                      {**_GEOM, "dtype": "bfloat16"})[0] != k1
    with pytest.raises(ValueError, match="unknown tune site"):
        T.tune_key("nosuch", _TOPO, _GEOM)


# ---------------------------------------------------------------------------
# Index journal fold law (the cache's discipline, verbatim)
# ---------------------------------------------------------------------------

def test_reduce_tune_journal_fold_law():
    events = [
        _put("k1", t=1.0), _put("k2", t=2.0, choice="I"),
        _put("k1", t=3.0, choice="E-uni"),  # re-put replaces
        {"event": "tune_invalidate", "key": "k2"},
        _put("k3", t=4.0),
    ]
    whole = T.reduce_tune_journal(events)
    for cut in range(len(events) + 1):
        state = T.reduce_tune_journal(events[:cut])
        folded = T.reduce_tune_journal(events[cut:], state=state)
        assert folded == whole
    entries, anomalies = whole
    assert set(entries) == {"k1", "k3"}
    assert entries["k1"]["choice"] == "E-uni"
    assert entries["k1"]["put_t"] == 3.0
    assert anomalies == []


def test_reduce_tune_journal_unknown_invalidate_anomaly():
    _, anomalies = T.reduce_tune_journal(
        [{"event": "tune_invalidate", "key": "ghost"}])
    assert len(anomalies) == 1 and "unknown entry ghost" in anomalies[0]


def test_reduce_tune_journal_ignores_foreign_lines():
    entries, anomalies = T.reduce_tune_journal([
        {"event": "mystery", "key": "k1"},
        {"event": "tune_put"},  # no key
        {"not": "an event"},
    ])
    assert entries == {} and anomalies == []


# ---------------------------------------------------------------------------
# DB round-trip, torn tail, crash windows
# ---------------------------------------------------------------------------

def test_tune_db_put_lookup_roundtrip(tmp_path):
    with T.TuneDB(str(tmp_path)) as db:
        entry = db.put("single_2d", _TOPO, _GEOM, choice="E",
                       detail=8, verified=True,
                       candidates=[{"choice": "E",
                                    "bitwise_verified": True}],
                       protocol={"timer": "interleaved_min_of_n"})
        hit, reason = db.lookup("single_2d", _TOPO, _GEOM)
        assert reason is None and hit["choice"] == "E"
        # The record file carries the full evidence table.
        with open(db.record_path(entry["key"])) as f:
            rec = json.load(f)
        assert rec["candidates"][0]["bitwise_verified"] is True
        assert rec["canon"]["geometry"] == _GEOM
        # A different geometry is a clean miss, never a reject.
        assert db.lookup("single_2d", _TOPO,
                         {**_GEOM, "shape": [128, 128]}) == (None, None)
        # The vocabulary is enforced at admission, not just consult.
        with pytest.raises(ValueError, match="proven-bitwise"):
            db.put("single_2d", _TOPO, _GEOM, choice="G-uni",
                   verified=True)
    # Cold reload folds to the same state (fresh process).
    entries, anomalies, bad, torn = tune.load_tune_db(str(tmp_path))
    assert anomalies == [] and bad == 0 and not torn
    assert entries[entry["key"]]["choice"] == "E"


def test_tune_db_torn_tail_invisible(tmp_path):
    with T.TuneDB(str(tmp_path)) as db:
        db.put("single_2d", _TOPO, _GEOM, choice="E", verified=True)
    with open(tmp_path / "index.jsonl", "a") as f:
        f.write('{"event": "tune_put", "key": "torn')  # no newline
    entries, anomalies, bad, torn = tune.load_tune_db(str(tmp_path))
    assert len(entries) == 1 and anomalies == [] and bad == 0 and torn
    # The incremental fold consumes whole lines only: a fresh handle
    # sees the same single entry, and completing the tail later would
    # surface it (no byte is ever skipped).
    db2 = T.TuneDB(str(tmp_path))
    assert len(db2.entries()) == 1
    db2.close()


def test_crash_window_record_without_index_line(tmp_path):
    # A crash between the record rename-commit and the index append
    # loses the ENTRY (the search re-runs) — the record is an orphan,
    # swept, never served.
    db = T.TuneDB(str(tmp_path))
    key, _ = T.tune_key("single_2d", _TOPO, _GEOM)
    with open(db.record_path(key), "w") as f:
        json.dump({"key": key, "choice": "E"}, f)
    assert db.entries() == {}
    assert db.lookup("single_2d", _TOPO, _GEOM) == (None, None)
    assert db.sweep_orphans() == 1
    assert not os.path.exists(db.record_path(key))
    db.close()


def test_crash_window_invalidate_line_before_record_delete(tmp_path):
    # Invalidate commits its index line BEFORE the record delete: a
    # crash between the two leaves an orphan record — folded state
    # shows no entry, and the sweep removes the residue.
    db = T.TuneDB(str(tmp_path))
    entry = db.put("single_2d", _TOPO, _GEOM, choice="E",
                   verified=True)
    db.journal.append("tune_invalidate", key=entry["key"])
    db.close()
    db2 = T.TuneDB(str(tmp_path))
    assert db2.entries() == {}
    assert db2.anomalies() == []
    assert os.path.exists(db2.record_path(entry["key"]))  # the residue
    assert db2.sweep_orphans() == 1
    db2.close()


# ---------------------------------------------------------------------------
# Doctored / unverified evidence -> reject with a reason (the loud-
# fallback feed)
# ---------------------------------------------------------------------------

def test_lookup_rejects_unverified_winner(tmp_path):
    with T.TuneDB(str(tmp_path)) as db:
        db.put("single_2d", _TOPO, _GEOM, choice="jnp", verified=False)
        entry, reason = db.lookup("single_2d", _TOPO, _GEOM)
        assert entry is None and "not bitwise-verified" in reason


def test_lookup_rejects_doctored_record(tmp_path):
    with T.TuneDB(str(tmp_path)) as db:
        e = db.put("single_2d", _TOPO, _GEOM, choice="E",
                   verified=True)
        # Evidence disagreeing with the index line: rejected.
        with open(db.record_path(e["key"]), "w") as f:
            json.dump({"key": e["key"], "choice": "I"}, f)
        entry, reason = db.lookup("single_2d", _TOPO, _GEOM)
        assert entry is None and "doctored or stale" in reason
        # Torn/corrupt record: rejected.
        with open(db.record_path(e["key"]), "w") as f:
            f.write('{"key": "tor')
        entry, reason = db.lookup("single_2d", _TOPO, _GEOM)
        assert entry is None and "missing/torn" in reason


def test_lookup_rejects_schema_drift(tmp_path):
    db = T.TuneDB(str(tmp_path))
    e = db.put("single_2d", _TOPO, _GEOM, choice="E", verified=True)
    db.journal.append(
        "tune_put", key=e["key"], db_schema=T.TUNE_SCHEMA_VERSION + 1,
        site="single_2d", topology=_TOPO, geometry=_GEOM, choice="E",
        detail=None, verified=True, n_candidates=0,
        record=f"{e['key']}.json")
    db._consume([])  # advance past the raw append
    db2 = T.TuneDB(str(tmp_path))
    entry, reason = db2.lookup("single_2d", _TOPO, _GEOM)
    assert entry is None and "schema" in reason
    db.close()
    db2.close()


# ---------------------------------------------------------------------------
# Consult layer: force pins, tuned picks, loud analytic fallback
# ---------------------------------------------------------------------------

def _cfg64(**kw):
    kw.setdefault("steps", 4)
    return HeatConfig(nx=64, ny=64, backend="pallas",
                      **kw).validate()


def test_force_vocabulary_guard():
    with pytest.raises(ValueError, match="outside site"):
        with tune.force("single_2d", "nosuch"):
            pass


def test_force_pins_the_real_picker():
    from parallel_heat_tpu.ops import pallas_stencil as ps

    cfg = _cfg64()
    with tune.force("single_2d", "jnp"):
        kind, detail = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1,
                                         0.1)
    assert (kind, detail) == ("jnp", None)
    with tune.force("single_2d", "E"):
        kind, detail = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1,
                                         0.1)
    assert kind == "E" and isinstance(detail, int)


def test_consult_uses_verified_entry_and_explain_reports(tmp_path):
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.ops import pallas_stencil as ps

    cfg = _cfg64()
    geom = tune.geometry_single_2d(cfg.shape, cfg.dtype,
                                   cfg.accumulate)
    with T.TuneDB(str(tmp_path)) as db:
        db.put("single_2d", tune.current_topology(), geom, choice="E",
               verified=True)
    tune.set_active(str(tmp_path))
    kind, detail = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1, 0.1)
    assert kind == "E"
    # detail is re-derived live, never read from the entry.
    assert isinstance(detail, int)
    ex = solver.explain(cfg)
    d = ex["decided_by"]["single_2d"]
    assert d["source"] == "tuned-db" and d["choice"] == "E"
    assert d["entry"] == T.tune_key("single_2d",
                                    tune.current_topology(), geom)[0]
    tune.set_active(None)
    ex2 = solver.explain(cfg)
    assert ex2["decided_by"]["single_2d"]["source"] == "analytic-model"


def test_doctored_db_falls_back_loudly_to_analytic(tmp_path):
    from parallel_heat_tpu.ops import pallas_stencil as ps

    cfg = _cfg64()
    analytic_kind, _ = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1,
                                         0.1)
    geom = tune.geometry_single_2d(cfg.shape, cfg.dtype,
                                   cfg.accumulate)
    with T.TuneDB(str(tmp_path)) as db:
        # An unverified winner for THIS topology+geometry: the picker
        # must warn and run the analytic choice — never the unverified
        # schedule.
        db.put("single_2d", tune.current_topology(), geom,
               choice="jnp", verified=False)
    tune.set_active(str(tmp_path))
    with pytest.warns(RuntimeWarning,
                      match="falling back to analytic"):
        kind, _ = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1, 0.1)
    assert kind == analytic_kind
    assert kind != "jnp"


def test_stale_infeasible_entry_falls_back_loudly(tmp_path):
    from parallel_heat_tpu.ops import pallas_stencil as ps

    cfg = _cfg64()
    analytic_kind, _ = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1,
                                         0.1)
    geom = tune.geometry_single_2d(cfg.shape, cfg.dtype,
                                   cfg.accumulate)
    with T.TuneDB(str(tmp_path)) as db:
        # A verified entry whose choice the builders now decline for
        # this geometry (C never admits 64x64 here): advisory-only —
        # the picker re-checks feasibility and falls back loudly.
        db.put("single_2d", tune.current_topology(), geom, choice="C",
               verified=True)
    tune.set_active(str(tmp_path))
    with pytest.warns(RuntimeWarning,
                      match="falling back to analytic"):
        kind, _ = ps.pick_single_2d(cfg.shape, cfg.dtype, 0.1, 0.1)
    assert kind == analytic_kind


# ---------------------------------------------------------------------------
# Bitwise parity sweep: every DB-selectable single-grid schedule on one
# geometry produces the identical grid (the contract that makes tuned
# selection results-invariant BY CONSTRUCTION).
# ---------------------------------------------------------------------------

def test_parity_sweep_every_db_selectable_single_2d_schedule():
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.tune.search import picked_kind

    cfg = HeatConfig(nx=256, ny=256, steps=6,
                     backend="pallas").validate()
    reference = None
    swept = []
    for choice in tune.SITE_CHOICES["single_2d"]:
        if choice == "jnp":
            continue  # the non-Pallas fallback is allclose, not bitwise
        if picked_kind("single_2d", cfg, choice) != choice:
            continue  # infeasible on this geometry (e.g. C)
        # The runner memo keys on config ALONE: without the clear every
        # solve after the first reuses the first choice's compiled
        # program and the parity claim is vacuous (each grid would be
        # compared with itself).
        solver._build_runner.cache_clear()
        with tune.force("single_2d", choice):
            grid = np.asarray(solver.solve(cfg).grid)
        if reference is None:
            reference = grid
        else:
            assert np.array_equal(grid, reference), (
                f"schedule {choice} diverged bitwise")
        swept.append(choice)
    # The sweep must actually cover the kernel family, or the parity
    # claim is vacuous.
    assert {"A", "E", "E-uni", "I", "I-uni", "B"} <= set(swept)


# ---------------------------------------------------------------------------
# HL101 partition: toggling the DB never perturbs the runner cache
# ---------------------------------------------------------------------------

def test_db_toggle_causes_zero_new_runner_cache_misses(tmp_path):
    from parallel_heat_tpu import solver

    cfg = _cfg64()
    geom = tune.geometry_single_2d(cfg.shape, cfg.dtype,
                                   cfg.accumulate)
    with T.TuneDB(str(tmp_path)) as db:
        db.put("single_2d", tune.current_topology(), geom, choice="E",
               verified=True)
    solver._build_runner.cache_clear()
    solver.solve(cfg)
    baseline = solver._build_runner.cache_info()
    tune.set_active(str(tmp_path))
    solver.solve(cfg)
    with_db = solver._build_runner.cache_info()
    tune.set_active(None)
    solver.solve(cfg)
    without = solver._build_runner.cache_info()
    assert with_db.misses == baseline.misses
    assert without.misses == baseline.misses
    assert without.hits == baseline.hits + 2


# ---------------------------------------------------------------------------
# The search harness end to end (tiny geometry; the verify gate and the
# DB round-trip, not the timings, are the contract on CPU)
# ---------------------------------------------------------------------------

def test_search_site_verifies_before_timing_and_persists(tmp_path):
    from parallel_heat_tpu.tune.search import search_site

    cfg = _cfg64(steps=8)
    with T.TuneDB(str(tmp_path)) as db:
        report = search_site(cfg, "single_2d", rounds=1,
                             steps_per_call=4, db=db)
        by = {c["choice"]: c for c in report["candidates"]}
        # Every feasible Pallas candidate is bitwise-verified against
        # the analytic reference; the jnp fallback never is (allclose
        # only), so it can never win on a Pallas geometry.
        for c, row in by.items():
            if row["feasible"] and c != "jnp":
                assert row["bitwise_verified"], row
        assert not by["jnp"]["bitwise_verified"]
        assert by["jnp"]["min_wall_s"] is None  # excluded from timing
        assert report["winner"] != "jnp"
        assert by[report["winner"]]["bitwise_verified"]
        assert report["protocol"]["reference"] == (
            f"analytic:{report['analytic_choice']}")
        # Persisted winner consults back through the public lookup.
        entry, reason = db.lookup("single_2d", report["topology"],
                                  report["geometry"])
        assert reason is None
        assert entry["choice"] == report["winner"]
        assert entry["key"] == report["db_key"]


def test_candidate_fn_builds_driver_candidates_under_their_own_pin():
    """Each driver-level candidate's compiled runner is built under ITS
    pin. ``solver._build_runner`` memoizes on config alone and every
    candidate shares the config, so without the clear-around-build in
    ``_candidate_fn`` the second candidate would silently reuse the
    first candidate's compiled schedule (and never consult the picker
    at all — which is exactly what the decision recorder pins here)."""
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.tune import search

    cfg = HeatConfig(nx=64, ny=64, steps=4, backend="jnp",
                     mesh_shape=(1, 2)).validate()
    for choice in ("phase", "overlap"):
        with tune.record() as notes:
            fn = search._candidate_fn("halo_overlap", cfg, choice, 4)
        assert {"site": "halo_overlap", "source": "forced",
                "choice": choice} in [
            {k: n.get(k) for k in ("site", "source", "choice")}
            for n in notes], (choice, notes)
        del fn
    # No forced runner may leak into production state.
    assert solver._build_runner.cache_info().currsize == 0


def test_search_site_halo_overlap_races_distinct_verified_schedules(
        tmp_path):
    """End-to-end driver-level search: the exchange schedules are
    bitwise-identical by the PR-17 contract, so every feasible
    candidate must verify, get timed, and the winner persists — under
    a geometry key the consult site can actually find (the search
    resolves the auto halo depth exactly like ``solver._resolved``
    does at pick time; a key built from the raw config's ``None``
    depth could never be consulted back)."""
    from parallel_heat_tpu import solver
    from parallel_heat_tpu.tune.search import search_site

    cfg = HeatConfig(nx=64, ny=64, steps=4, backend="jnp",
                     mesh_shape=(1, 2)).validate()
    with T.TuneDB(str(tmp_path)) as db:
        report = search_site(cfg, "halo_overlap", rounds=1, db=db)
        by = {c["choice"]: c for c in report["candidates"]}
        assert by["phase"]["feasible"] and by["overlap"]["feasible"]
        for c in ("phase", "overlap"):
            assert by[c]["bitwise_verified"], by[c]
            assert by[c]["min_wall_s"] is not None
        assert by[report["winner"]]["bitwise_verified"]
        entry, reason = db.lookup("halo_overlap", report["topology"],
                                  report["geometry"])
        assert reason is None
        assert entry["choice"] == report["winner"]
    # The searched entry consults back through a production resolve.
    tune.set_active(str(tmp_path))
    try:
        ex = solver.explain(cfg)
        d = ex["decided_by"]["halo_overlap"]
        assert d["source"] == "tuned-db", d
        assert d["entry"] == report["db_key"]
        assert d["choice"] == report["winner"]
    finally:
        tune.set_active(None)


def test_search_site_ensemble_times_the_batched_engine_path(tmp_path):
    """The ensemble_2d search must race the ENGINE's member-batched
    programs — a plain solve never consults ``pick_ensemble_2d``. At
    64² f32 kernel M admits (the analytic choice) and the vmap
    candidate runs the jnp spelling, which is allclose-only against
    the Pallas kernels on this geometry (the same pin as the solo
    jnp row above): the two candidates producing DIFFERENT bits is
    itself the proof that two genuinely distinct batched programs
    ran, not one cached program twice."""
    from parallel_heat_tpu.tune.search import picked_kind, search_site

    cfg = _cfg64(steps=8)
    assert picked_kind("ensemble_2d", cfg) == "M"
    with T.TuneDB(str(tmp_path)) as db:
        report = search_site(cfg, "ensemble_2d", rounds=1,
                             steps_per_call=4, members=2, db=db)
        by = {c["choice"]: c for c in report["candidates"]}
        assert report["analytic_choice"] == "M"
        assert by["M"]["feasible"] and by["vmap"]["feasible"]
        assert by["M"]["bitwise_verified"]
        assert not by["vmap"]["bitwise_verified"]
        assert by["vmap"]["min_wall_s"] is None  # never timed, never wins
        assert report["winner"] == "M"
        assert report["protocol"]["members"] == 2
        entry, reason = db.lookup("ensemble_2d", report["topology"],
                                  report["geometry"])
        assert reason is None
        assert entry["choice"] == "M"


# ---------------------------------------------------------------------------
# measure.py satellites: the shared timing protocol's new entry points
# ---------------------------------------------------------------------------

def test_interleaved_min_self_timed_round_robins():
    from parallel_heat_tpu.utils import measure

    calls = []
    fns = {"a": lambda: calls.append("a") or 3.0 - len(calls),
           "b": lambda: calls.append("b") or 10.0 + len(calls)}
    out = measure.interleaved_min_self_timed(fns, rounds=3)
    # Interleaved a,b,a,b,a,b — never a,a,a,b,b,b (drift fairness).
    assert calls == ["a", "b"] * 3
    assert out == {"a": 3.0 - 5, "b": 10.0 + 2}


def test_profiling_reexports_measure_protocol():
    # bench.py / tools ports moved the protocol to utils/measure.py;
    # profiling keeps the old names as aliases so existing callers and
    # artifacts stay valid.
    from parallel_heat_tpu.utils import measure, profiling

    assert profiling.bench_rounds_paired is measure.bench_rounds_paired
    assert profiling.chain_slope is measure.chain_slope
    assert profiling.sync is measure.sync
