"""Seeded configuration fuzz: random geometries x modes x backends.

Breadth supplement to the systematic suites: each case draws a config
from a seeded RNG and checks the core invariants — sharded == single
(bitwise, jnp), pallas == jnp (few-ulp), converge metadata consistency.
Seeds are fixed so failures reproduce; add seeds when a fuzz case ever
catches something.
"""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.config import sublane_count

_MESHES = [None, (2, 1), (1, 2), (2, 2), (4, 2), (2, 4), (8, 1)]


def _random_config(rng):
    nx = int(rng.integers(3, 12)) * int(rng.choice([1, 2, 4]))
    ny = int(rng.integers(3, 12)) * int(rng.choice([1, 2, 4]))
    mesh = _MESHES[int(rng.integers(0, len(_MESHES)))]
    if mesh is not None:
        nx = max(nx, mesh[0]) * mesh[0]
        ny = max(ny, mesh[1]) * mesh[1]
    converge = bool(rng.integers(0, 2))
    cfg = HeatConfig(
        nx=nx, ny=ny,
        steps=int(rng.integers(0, 40)),
        cx=float(rng.uniform(0.01, 0.24)),
        cy=float(rng.uniform(0.01, 0.24)),
        converge=converge,
        check_interval=int(rng.integers(1, 9)),
        eps=10.0 ** float(rng.integers(-6, -1)),
        dtype=str(rng.choice(["float32", "bfloat16"])),
        mesh_shape=mesh,
        overlap=bool(rng.integers(0, 2)),
        backend="jnp",
    )
    if mesh is not None and bool(rng.integers(0, 2)):
        depth = int(rng.integers(2, 9))
        bmin = min(cfg.block_shape())
        if depth <= bmin:
            cfg = cfg.replace(halo_depth=depth)
    return cfg.validate()


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_sharded_equals_single(seed):
    rng = np.random.default_rng(1000 + seed)
    cfg = _random_config(rng)
    got = solve(cfg)
    want = solve(cfg.replace(mesh_shape=None, halo_depth=1))
    assert got.steps_run == want.steps_run, cfg
    assert got.converged == want.converged, cfg
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy(),
                                  err_msg=repr(cfg))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_pallas_matches_jnp(seed):
    rng = np.random.default_rng(2000 + seed)
    cfg = _random_config(rng).replace(mesh_shape=None, halo_depth=1,
                                      steps=int(rng.integers(1, 20)))
    want = solve(cfg)
    got = solve(cfg.replace(backend="pallas"))
    assert got.steps_run == want.steps_run, cfg
    tol = dict(rtol=5e-2, atol=0.5) if cfg.dtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(got.to_numpy().astype(np.float64),
                               want.to_numpy().astype(np.float64),
                               err_msg=repr(cfg), **tol)


_MESHES_3D = [None, (2, 1, 1), (1, 2, 2), (2, 2, 2), (1, 1, 8)]


def _random_config_3d(rng):
    dims = [int(rng.integers(3, 8)) * int(rng.choice([1, 2])) for _ in range(3)]
    mesh = _MESHES_3D[int(rng.integers(0, len(_MESHES_3D)))]
    if mesh is not None:
        dims = [max(d, m) * m for d, m in zip(dims, mesh)]
    cfg = HeatConfig(
        nx=dims[0], ny=dims[1], nz=dims[2],
        steps=int(rng.integers(0, 20)),
        cx=float(rng.uniform(0.01, 0.15)),
        cy=float(rng.uniform(0.01, 0.15)),
        cz=float(rng.uniform(0.01, 0.15)),
        converge=bool(rng.integers(0, 2)),
        check_interval=int(rng.integers(1, 7)),
        dtype=str(rng.choice(["float32", "bfloat16"])),
        mesh_shape=mesh,
        backend="jnp",
    )
    if mesh is not None and bool(rng.integers(0, 2)):
        depth = int(rng.integers(2, 6))
        if depth <= min(cfg.block_shape()):
            cfg = cfg.replace(halo_depth=depth)
    return cfg.validate()


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_3d_sharded_equals_single(seed):
    rng = np.random.default_rng(3000 + seed)
    cfg = _random_config_3d(rng)
    got = solve(cfg)
    want = solve(cfg.replace(mesh_shape=None, halo_depth=1))
    assert got.steps_run == want.steps_run, cfg
    assert got.converged == want.converged, cfg
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy(),
                                  err_msg=repr(cfg))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_sharded_pallas_temporal_matches_jnp(seed):
    # Sharded pallas with K-deep rounds (kernels G/H in interpret
    # mode, jnp rounds where they decline) vs the single-device jnp
    # oracle — the fuzz coverage for the round-2 shard-block kernels.
    rng = np.random.default_rng(4000 + seed)
    three_d = bool(rng.integers(0, 2))
    cfg = (_random_config_3d(rng) if three_d else _random_config(rng))
    if cfg.mesh_shape is None:
        mesh = (2, 2, 2) if three_d else (2, 2)
        dims = [max(4, d // m * m) for d, m in zip(cfg.shape, mesh)]
        kw = dict(nx=dims[0], ny=dims[1])
        if three_d:
            kw["nz"] = dims[2]
        cfg = cfg.replace(mesh_shape=mesh, **kw)
    sub = sublane_count(cfg.dtype)
    if three_d:  # kernel H accepts any depth
        depth = int(rng.choice([2, 3, sub]))
    else:  # 2D pallas requires depth == sublane count (kernel G)
        depth = sub
    if depth > min(cfg.block_shape()):
        depth = None  # let the solver auto-resolve a legal depth
    cfg = cfg.replace(backend="pallas", halo_depth=depth,
                      steps=int(rng.integers(1, 25))).validate()
    got = solve(cfg)
    want = solve(cfg.replace(backend="jnp", mesh_shape=None,
                             halo_depth=1))
    assert got.steps_run == want.steps_run, cfg
    tol = dict(rtol=5e-2, atol=2.0) if cfg.dtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(got.to_numpy().astype(np.float64),
                               want.to_numpy().astype(np.float64),
                               err_msg=repr(cfg), **tol)
