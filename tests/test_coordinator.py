"""The distributed-supervision consensus layer, without process
boundaries: unit tests of the merges/KV/liveness machinery plus
THREAD-SIMULATED ranks driving the full supervised loop through a
shared :class:`InMemoryKV` — the split-brain, two-phase-commit and
peer-lost contracts are certified here cheaply; the real 2-process
gloo certification lives in the ``mp_split_brain`` / ``mp_peer_lost``
chaos cells (tools/chaos_matrix.py, ``make mp-smoke``)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from parallel_heat_tpu import (
    HeatConfig,
    SupervisorPolicy,
    Telemetry,
    run_supervised,
    solve,
)
from parallel_heat_tpu.parallel.coordinator import (
    Coordinator,
    InMemoryKV,
    KVCoordinator,
    PeerLostError,
    PeerTransientError,
    heartbeat_path_for,
    merge_boundary,
    merge_stats,
    surviving_mesh_shape,
)
from parallel_heat_tpu.utils.checkpoint import (
    StemLockError,
    acquire_stem_lock,
    generation_paths,
    latest_checkpoint,
    load_checkpoint,
    save_generation_coordinated,
)
from parallel_heat_tpu.utils.faults import FaultPlan, InjectedTransientError

_BASE = dict(nx=16, ny=16, backend="jnp")


# ---------------------------------------------------------------------------
# Pure merges
# ---------------------------------------------------------------------------

def test_merge_boundary_identity_for_single_rank():
    # THE single-process parity property: a merge of one verdict is
    # that verdict, field for field.
    v = {"stop": 15, "fault": None, "err": None, "finite": True}
    assert merge_boundary([v]) == v
    assert merge_boundary([{}]) == {"stop": None, "fault": None,
                                    "err": None, "finite": None}


def test_merge_boundary_worst_case_wins_deterministically():
    clean = {"finite": True}
    assert merge_boundary([clean, {"finite": False}])["finite"] is False
    assert merge_boundary([clean, clean])["finite"] is True
    # any rank's stop stops everyone; lowest rank's detail wins
    m = merge_boundary([{"stop": None}, {"stop": "deadline"}])
    assert m["stop"] == "deadline"
    m = merge_boundary([{"stop": 15}, {"stop": "deadline"}])
    assert m["stop"] == 15
    # faults/errs name the reporting rank
    m = merge_boundary([{}, {"err": "boom"}])
    assert m["err"] == "[rank 1] boom"
    # finite None (no guard this boundary) stays None
    assert merge_boundary([{}, {}])["finite"] is None


def test_merge_stats_partials():
    out = merge_stats([{"min": 0.0, "max": 2.0, "heat": 10.0},
                       {"min": -1.0, "max": 1.0, "heat": 5.0}])
    assert out == {"min": -1.0, "max": 2.0, "heat": 15.0}


def test_surviving_mesh_shape_divisibility():
    assert surviving_mesh_shape((32, 32), 4) == (2, 2)
    assert surviving_mesh_shape((32, 32), 1) is None
    # balanced pick (3, 1) divides 33x11? 33 % 3 == 0 -> fine
    assert surviving_mesh_shape((33, 11), 3) == (3, 1)
    # nothing divides a prime x prime grid except 1-ish factors
    assert surviving_mesh_shape((13, 7), 6) is None


# ---------------------------------------------------------------------------
# InMemoryKV + KVCoordinator liveness
# ---------------------------------------------------------------------------

def test_inmemory_kv_blocking_get_timeout():
    kv = InMemoryKV()
    with pytest.raises(TimeoutError):
        kv.blocking_key_value_get("missing", 50)
    kv.key_value_set("k", "v")
    assert kv.blocking_key_value_get("k", 50) == "v"
    kv.key_value_delete("k")
    with pytest.raises(TimeoutError):
        kv.blocking_key_value_get("k", 10)


def _pair(kv, **kw):
    kw.setdefault("barrier_timeout_s", 5.0)
    kw.setdefault("heartbeat_interval_s", 0.05)
    return (KVCoordinator(kv, 0, 2, **kw),
            KVCoordinator(kv, 1, 2, **kw))


def test_kv_exchange_rank_ordered_roundtrip():
    kv = InMemoryKV()
    c0, c1 = _pair(kv)
    out = {}

    def rank(c, payload):
        out[c.process_index] = c.exchange("verdict", payload)

    t = threading.Thread(target=rank, args=(c1, {"r": 1}))
    t.start()
    rank(c0, {"r": 0})
    t.join()
    c0.close(), c1.close()
    # both ranks see the identical rank-ordered list
    assert out[0] == out[1] == [{"r": 0}, {"r": 1}]


def test_kv_exchange_detects_dead_peer_within_timeout():
    kv = InMemoryKV()
    c0, c1 = _pair(kv, barrier_timeout_s=0.4)
    c1.close()  # rank 1 "dies": heartbeat stops changing
    t0 = time.monotonic()
    with pytest.raises(PeerLostError) as ei:
        c0.exchange("verdict", {"r": 0})
    waited = time.monotonic() - t0
    c0.close()
    assert ei.value.lost == (1,)
    assert waited < 5.0  # bounded, not a hang
    assert ei.value.timeout_s == 0.4


def test_kv_exchange_waits_for_slow_but_alive_peer():
    # A peer whose heartbeat keeps CHANGING extends the wait past the
    # barrier timeout — slow is not dead.
    kv = InMemoryKV()
    c0, c1 = _pair(kv, barrier_timeout_s=0.3,
                   heartbeat_interval_s=0.05)

    def late():
        time.sleep(0.9)  # 3x the barrier timeout, but heartbeating
        c1.exchange("verdict", {"r": 1})

    t = threading.Thread(target=late)
    t.start()
    out = c0.exchange("verdict", {"r": 0})
    t.join()
    c0.close(), c1.close()
    assert out == [{"r": 0}, {"r": 1}]


def test_kv_coordinator_heartbeat_file_format(tmp_path):
    # The probe file rides the telemetry heartbeat-file format and is
    # removed on clean close (a clean exit must read as gone, not as
    # freshly alive, to the stem lock's reclaim judgment).
    hb = str(tmp_path / "stem.hb.p0.json")
    kv = InMemoryKV()
    c = KVCoordinator(kv, 0, 2, heartbeat_interval_s=0.05,
                      heartbeat_path=hb)
    time.sleep(0.15)
    doc = json.load(open(hb))
    for key in ("t_wall", "t_mono", "pid", "events", "last_event",
                "interval_s", "process_index"):
        assert key in doc, key
    assert doc["pid"] == os.getpid() and doc["process_index"] == 0
    c.close()
    assert not os.path.exists(hb)
    assert heartbeat_path_for(str(tmp_path / "stem"), 1) \
        == str(tmp_path / "stem") + ".hb.p1.json"


# ---------------------------------------------------------------------------
# Stem lock: reclaim tied to peer heartbeats
# ---------------------------------------------------------------------------

def test_stem_lock_dead_holder_with_fresh_peer_heartbeat_not_reclaimed(
        tmp_path):
    # The multi-process gap (ISSUE 10 satellite): process 0 holds the
    # lock for the whole SPMD run; if it crashes while ranks >= 1 are
    # still streaming, the dead pid alone must NOT make the lock
    # reclaimable — a fresh peer heartbeat file keeps it held.
    stem = str(tmp_path / "ck")
    lock = tmp_path / "ck.lock"
    hb_glob = f"{stem}.hb.p*.json"
    lock.write_text(json.dumps(
        {"pid": 2 ** 30, "t_wall": 0.0,  # dead holder
         "hb_glob": hb_glob, "hb_timeout_s": 60.0}))
    with open(f"{stem}.hb.p1.json", "w") as f:  # fresh peer heartbeat
        json.dump({"t_wall": time.time(), "pid": os.getpid()}, f)
    with pytest.raises(StemLockError, match="peer ranks are still"):
        acquire_stem_lock(stem)
    # once the peer's beat goes stale, the lock is reclaimable
    old = time.time() - 3600
    os.utime(f"{stem}.hb.p1.json", (old, old))
    release = acquire_stem_lock(stem)
    release()


@pytest.mark.chaos
def test_restart_after_whole_pod_death_reclaims_stale_lock(tmp_path):
    # Regression (review finding): the restarting run must take the
    # dead predecessor's lock BEFORE its own coordinator heartbeat
    # probe files exist — the file names are identical across runs, so
    # writing <stem>.hb.pN.json first would make the new run's OWN
    # beat block reclaim forever. Simulate the whole-pod-death
    # aftermath (dead-pid lock recording an hb_glob, stale probe
    # files) and run a full thread-simulated supervised restart over
    # the same stem: it must reclaim, run, and complete.
    stem = str(tmp_path / "ck")
    hb_glob = f"{stem}.hb.p*.json"
    (tmp_path / "ck.lock").write_text(json.dumps(
        {"pid": 2 ** 30, "t_wall": 0.0,
         "hb_glob": hb_glob, "hb_timeout_s": 60.0}))
    old = time.time() - 3600
    for i in range(2):
        p = f"{stem}.hb.p{i}.json"
        with open(p, "w") as f:
            json.dump({"t_wall": old, "pid": 2 ** 30}, f)
        os.utime(p, (old, old))
    r0, r1 = _sim_run(tmp_path, lambda i: None)
    assert r0.steps_done == r1.steps_done == 60
    assert not r0.interrupted and not r1.interrupted
    # and the new run's own probe files were live during the run
    # (enabled after the lock was held), then removed on clean close
    assert not os.path.exists(f"{stem}.hb.p0.json") \
        or json.load(open(f"{stem}.hb.p0.json"))["t_wall"] > old


def test_stem_lock_records_heartbeat_glob(tmp_path):
    stem = str(tmp_path / "ck")
    release = acquire_stem_lock(stem, heartbeat_glob=f"{stem}.hb.p*.json",
                                heartbeat_timeout_s=12.0)
    doc = json.load(open(f"{stem}.lock"))
    assert doc["hb_glob"] == f"{stem}.hb.p*.json"
    assert doc["hb_timeout_s"] == 12.0
    release()


# ---------------------------------------------------------------------------
# Two-phase checkpoint commit (thread-simulated ranks)
# ---------------------------------------------------------------------------

def _run_ranks(fn, n=2):
    """Run fn(rank) on n threads; returns per-rank results, re-raising
    the first failure."""
    out = [None] * n
    errs = [None] * n

    def worker(i):
        try:
            out[i] = fn(i)
        except BaseException as e:  # noqa: BLE001
            errs[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    for e in errs:
        if e is not None:
            raise e
    return out


def test_two_phase_commit_skips_generation_globally(tmp_path):
    # Any rank's non-finite verdict must skip the generation on EVERY
    # rank (no global manifest/commit), leaving the previous
    # generation authoritative everywhere.
    cfg = HeatConfig(steps=4, **_BASE)
    good = solve(cfg).grid
    bad = np.asarray(good).copy()
    bad[3, 3] = np.nan
    kv = InMemoryKV()
    stem = str(tmp_path / "ck")

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=10.0)
        try:
            first = save_generation_coordinated(
                stem, good, 4, cfg, coord, keep=3)
            second = save_generation_coordinated(
                stem, bad if i == 1 else good, 8, cfg, coord, keep=3)
            return first, second
        finally:
            coord.close()

    (f0, s0), (f1, s1) = _run_ranks(rank)
    assert f0 == f1 and not f0[0] is None and f0[1] is False
    # the poisoned generation skipped globally, on both ranks
    assert s0 == s1 == (None, True)
    steps = [s for s, _ in generation_paths(stem)]
    assert steps == [4]  # generation 8 never committed
    grid, step, _ = load_checkpoint(latest_checkpoint(stem), cfg)
    assert step == 4


def test_two_phase_commit_rank0_writes_all_ranks_see_path(tmp_path):
    cfg = HeatConfig(steps=2, **_BASE)
    grid = solve(cfg).grid
    kv = InMemoryKV()
    stem = str(tmp_path / "ck")

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=10.0)
        try:
            return save_generation_coordinated(stem, grid, 2, cfg,
                                               coord, keep=3)
        finally:
            coord.close()

    (p0, sk0), (p1, sk1) = _run_ranks(rank)
    assert not sk0 and not sk1
    assert str(p0) == str(p1) and os.path.exists(str(p0))


# ---------------------------------------------------------------------------
# Thread-simulated SPMD supervision: the consensus contracts
# ---------------------------------------------------------------------------

def _sim_policy(**kw):
    kw.setdefault("checkpoint_every", 20)
    kw.setdefault("guard_interval", 10)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("barrier_timeout_s", 20.0)
    kw.setdefault("peer_heartbeat_s", 0.05)
    return SupervisorPolicy(**kw)


def _sim_run(tmp_path, rank_fault, tel=False, policy=None):
    """Two thread-ranks run the FULL supervised loop over one shared
    stem and a shared InMemoryKV; returns the per-rank
    SupervisorResults (plus telemetry paths when requested)."""
    kv = InMemoryKV()
    stem = tmp_path / "ck"
    cfg = HeatConfig(steps=60, **_BASE)

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=20.0,
                              heartbeat_interval_s=0.05)
        telemetry = None
        if tel:
            # shard_path suffixes .pN per rank: m.jsonl -> m.p0.jsonl
            telemetry = Telemetry(str(tmp_path / "m.jsonl"),
                                  process_index=i, process_count=2)
        try:
            return run_supervised(cfg, stem,
                                  policy=policy or _sim_policy(),
                                  faults=rank_fault(i),
                                  telemetry=telemetry,
                                  coordinator=coord)
        finally:
            if telemetry is not None:
                telemetry.close()
            coord.close()

    return _run_ranks(rank)


@pytest.mark.chaos
def test_consensus_single_rank_nan_rolls_back_both_ranks_bitwise(
        tmp_path):
    # THE split-brain cell, thread-simulated: the NaN lands on rank 1
    # only (only_process=1) — without consensus rank 1 would roll back
    # while rank 0 streams ahead. With it, both ranks trip at the SAME
    # boundary, roll back to the SAME generation, and recover BITWISE.
    clean = solve(HeatConfig(steps=60, **_BASE))
    r0, r1 = _sim_run(
        tmp_path, lambda i: FaultPlan(nan_at_step=35, only_process=1),
        tel=True)
    for sres in (r0, r1):
        assert sres.retries == 1 and sres.rollbacks == 1
        assert sres.guard_trips == 1
        assert sres.steps_done == 60
        np.testing.assert_array_equal(sres.result.to_numpy(),
                                      clean.to_numpy())
    assert r0.guard_trip_steps == r1.guard_trip_steps == (40,)
    # the artifacts agree: same consensus verdict, same rollback target
    per_rank = []
    for i in range(2):
        ev = [json.loads(l) for l in
              open(tmp_path / f"m.p{i}.jsonl")]
        cons = [e for e in ev if e["event"] == "consensus_verdict"]
        rbs = [e for e in ev if e["event"] == "rollback"]
        waits = [e for e in ev if e["event"] == "barrier_wait"]
        assert cons and cons[0]["action"] == "nan"
        # the envelope's rank is authoritative on EVERY event (schema
        # 2: run_header keeps jax's own view under runtime_process_*
        # instead of clobbering the envelope — thread-sim cannot fake
        # the runtime view, but the envelope it CAN set is what
        # heattrace lanes and the shard reports key off)
        assert all(e["process_index"] == i for e in ev)
        assert waits and all(w["wait_s"] >= 0 for w in waits)
        per_rank.append((cons[0]["step"], [r["path"] for r in rbs]))
    assert per_rank[0] == per_rank[1]


@pytest.mark.chaos
def test_consensus_single_rank_transient_rolls_back_both(tmp_path):
    # An injected pre-dispatch transient on rank 0 only: consensus
    # converts it into the identical rollback on rank 1 (as a
    # PeerTransientError under the same retry classifier).
    clean = solve(HeatConfig(steps=60, **_BASE))
    r0, r1 = _sim_run(
        tmp_path,
        lambda i: FaultPlan(transient_on_chunks=(2,), only_process=0))
    for sres in (r0, r1):
        assert sres.retries == 1 and sres.guard_trips == 0
        np.testing.assert_array_equal(sres.result.to_numpy(),
                                      clean.to_numpy())


@pytest.mark.chaos
def test_consensus_single_rank_interrupt_stops_both(tmp_path):
    # The caller's flag-only interrupt hook fires on rank 1 only; the
    # consensus stops BOTH ranks at the same boundary with the same
    # flushed state.
    kv = InMemoryKV()
    stem = tmp_path / "ck"
    cfg = HeatConfig(steps=60, **_BASE)

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=20.0,
                              heartbeat_interval_s=0.05)
        fired = {"n": 0}

        def interrupt():
            if i == 1:
                fired["n"] += 1
                if fired["n"] >= 3:
                    return "deadline"
            return None

        try:
            return run_supervised(cfg, stem, policy=_sim_policy(),
                                  interrupt=interrupt,
                                  coordinator=coord)
        finally:
            coord.close()

    r0, r1 = _run_ranks(rank)
    assert r0.interrupted and r1.interrupted
    assert r0.signal_name == r1.signal_name == "deadline"
    assert r0.steps_done == r1.steps_done > 0
    # the flushed checkpoint resumes bit-exactly (single-process now)
    clean = solve(cfg)
    grid, step, _ = load_checkpoint(latest_checkpoint(stem), cfg)
    sres = run_supervised(cfg.replace(steps=60 - step), stem,
                          policy=_sim_policy(), initial=grid,
                          start_step=step)
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())


@pytest.mark.chaos
def test_peer_crash_yields_bounded_peer_lost_and_elastic_resume(
        tmp_path):
    # Rank 1 dies hard (an unexpected error tears down its supervised
    # run and its coordinator — heartbeats stop). Rank 0 must exit
    # preempted within the barrier timeout with signal "peer_lost" and
    # an ELASTIC resume command, and that resume must complete
    # bit-exactly.
    kv = InMemoryKV()
    stem = tmp_path / "ck"
    cfg = HeatConfig(steps=60, **_BASE)
    clean = solve(cfg)

    class CrashPlan:
        """before_chunk raises a NON-transient error at ordinal 2 —
        the supervised run (and with it the coordinator's heartbeat)
        dies exactly like a host loss, minus the SIGKILL the real
        mp_peer_lost chaos cell delivers."""

        def __init__(self):
            self.n = 0

        def before_chunk(self):
            self.n += 1
            if self.n >= 3:
                raise RuntimeError("simulated host loss")

        def corrupt(self, grid, step, observed=True):
            return grid

    out = [None, None]
    crash = [None]

    def rank(i):
        coord = KVCoordinator(kv, i, 2, barrier_timeout_s=1.0,
                              heartbeat_interval_s=0.05)
        try:
            out[i] = run_supervised(
                cfg, stem,
                policy=_sim_policy(barrier_timeout_s=1.0),
                faults=CrashPlan() if i == 1 else None,
                coordinator=coord)
        except RuntimeError as e:
            crash[0] = e  # rank 1's host loss — expected
        finally:
            coord.close()

    t1 = threading.Thread(target=rank, args=(1,))
    t1.start()
    t0 = time.monotonic()
    rank(0)
    elapsed = time.monotonic() - t0
    t1.join()
    assert "simulated host loss" in str(crash[0])
    sres = out[0]
    assert sres.interrupted and sres.signal_name == "peer_lost"
    assert "--resume auto" in sres.resume_command
    assert "--mesh" in sres.resume_command  # elastic: a surviving mesh
    assert elapsed < 30.0  # bounded, not a wedge
    # elastic resume on the "surviving host" (single-process):
    grid, step, _ = load_checkpoint(latest_checkpoint(stem), cfg)
    res = run_supervised(cfg.replace(steps=60 - step), stem,
                         policy=_sim_policy(), initial=grid,
                         start_step=step)
    np.testing.assert_array_equal(res.result.to_numpy(),
                                  clean.to_numpy())


@pytest.mark.chaos
def test_single_process_kv_coordinator_is_bitwise_local(tmp_path):
    # A KV coordinator with process_count == 1 must behave exactly
    # like the identity coordinator: same result bitwise, same
    # generation layout — the consensus layer provably adds nothing.
    cfg = HeatConfig(steps=60, **_BASE)
    a = run_supervised(cfg, tmp_path / "a", policy=_sim_policy())
    coord = KVCoordinator(InMemoryKV(), 0, 1)
    try:
        b = run_supervised(cfg, tmp_path / "b", policy=_sim_policy(),
                           coordinator=coord)
    finally:
        coord.close()
    np.testing.assert_array_equal(a.result.to_numpy(),
                                  b.result.to_numpy())
    assert [s for s, _ in generation_paths(tmp_path / "a")] \
        == [s for s, _ in generation_paths(tmp_path / "b")]


def test_fault_plan_rank_scoping_and_kill_exclusivity():
    plan = FaultPlan(nan_at_step=5, only_process=1).bind_process(0)
    # non-matching rank: hooks are no-ops but ordinals still advance
    assert plan.before_chunk() == 0 and plan.before_chunk() == 1
    grid = np.ones((4, 4), np.float32)
    out = plan.corrupt(grid, 10)
    assert np.isfinite(np.asarray(out)).all()
    plan.bind_process(1)
    out = plan.corrupt(grid, 10)
    assert not np.isfinite(np.asarray(out)).all()
    with pytest.raises(ValueError, match="not both"):
        FaultPlan(kill_worker_at_chunk=1, kill_process_at_chunk=2)
    with pytest.raises(ValueError, match="true process death"):
        FaultPlan(kill_process_at_chunk=1, nan_at_step=5)


def test_peer_transient_error_is_retry_classified():
    from parallel_heat_tpu.supervisor import _is_transient_dispatch_error

    assert isinstance(PeerTransientError("x"), InjectedTransientError)
    assert _is_transient_dispatch_error(PeerTransientError("x"))


def test_local_coordinator_identity():
    c = Coordinator()
    assert not c.distributed
    assert c.exchange("anything", {"a": 1}) == [{"a": 1}]
    c.close()  # no-op
