"""Implicit time-stepping via the multigrid V-cycle (SEMANTICS.md
"Implicit stepping").

The contracts pinned here:

- **accuracy**: backward Euler tracks the explicit trajectory at the
  same dt (the schemes differ at O(dt)); at 100x the explicit-stable
  dt the run stays finite and lands within the documented tolerance
  of the explicit reference at the same physical time, where explicit
  at that dt diverges to inf;
- **order**: Crank-Nicolson's error against a fine-dt reference is
  strictly below backward Euler's at the same large dt (second vs
  first order);
- **bitwise pins**: run-to-run reproducibility; sharded (the 8-device
  CPU mesh) vs single-device bitwise equality of the same spec;
  chunked stream vs one-shot bitwise equality; observation-only
  toggles (guard/diag/pipeline) cause ZERO new ``_build_runner``
  misses and move no bits;
- **machinery transfer**: converge mode's residual loop drives
  implicit steps unchanged; the ensemble engine batches V-cycles over
  members bitwise the solo member; the Pallas transfer kernels are
  (in interpreter mode) bitwise the jnp spelling, so the pallas
  backend's implicit solve equals the jnp backend's exactly;
- **observability**: ``vcycle`` telemetry events carry cycles,
  per-cycle residuals, the contraction factor and (once per stream)
  the measured per-level wall shares; ``solver.explain`` reports the
  hierarchy the builder actually constructs;
- **serving**: heatd's HBM admission prices the level hierarchy on
  top of the explicit estimate, from the same jax-free level-shape
  source of truth.
"""

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from parallel_heat_tpu.config import (
    HeatConfig,
    multigrid_level_shapes,
)
from parallel_heat_tpu.solver import explain, solve, solve_stream

_ACC = jnp.float32


def _solve_grid(cfg, **kw):
    return solve(cfg.validate(), **kw).to_numpy()


# ---------------------------------------------------------------------------
# Hierarchy geometry
# ---------------------------------------------------------------------------

def test_level_shapes_halving_and_floor():
    assert multigrid_level_shapes((34, 34)) == [
        (34, 34), (18, 18), (10, 10), (6, 6)]
    # Odd interiors coarsen too (m // 2), down to the 3-cell floor.
    assert multigrid_level_shapes((513, 9)) == [(513, 9), (257, 5)]
    # mg_levels caps the depth.
    assert multigrid_level_shapes((34, 34), 2) == [(34, 34), (18, 18)]
    # Too small to coarsen: single-level hierarchy (smoother-only).
    assert multigrid_level_shapes((5, 5)) == [(5, 5)]


# ---------------------------------------------------------------------------
# Validation and the stability-warning escape hatch (satellite)
# ---------------------------------------------------------------------------

def test_scheme_validation_rejections():
    with pytest.raises(ValueError, match="scheme must be one of"):
        HeatConfig(scheme="midpoint").validate()
    with pytest.raises(ValueError, match="only apply to the implicit"):
        HeatConfig(mg_tol=1e-6).validate()  # mg knob on explicit
    with pytest.raises(ValueError, match="2D-only"):
        HeatConfig(nz=8, scheme="backward_euler").validate()
    with pytest.raises(ValueError, match="f32chunk"):
        HeatConfig(scheme="backward_euler", dtype="bfloat16",
                   accumulate="f32chunk").validate()
    with pytest.raises(ValueError, match="explicit-scheme exchange"):
        HeatConfig(nx=32, ny=32, scheme="backward_euler",
                   halo_depth=8).validate()
    with pytest.raises(ValueError, match="does not apply"):
        HeatConfig(scheme="crank_nicolson",
                   halo_overlap="pipeline").validate()
    with pytest.raises(ValueError, match="overlap=False"):
        HeatConfig(scheme="backward_euler", overlap=False).validate()
    # halo_depth=1 (the per-sweep exchange) is the resolved value and
    # must validate — solver._resolved substitutes it.
    HeatConfig(scheme="backward_euler", halo_depth=1).validate()


def test_stability_warning_names_implicit_escape_hatch():
    # Satellite contract: the bound-violation warning is actionable —
    # it names the --scheme backward_euler escape hatch; implicit
    # schemes (unconditionally stable) never warn.
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        HeatConfig(cx=0.4, cy=0.4).validate()
    msgs = [str(x.message) for x in w]
    assert any("stability bound" in m and "--scheme backward_euler"
               in m for m in msgs), msgs
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        HeatConfig(cx=0.4, cy=0.4, scheme="backward_euler").validate()
        HeatConfig(cx=40.0, cy=40.0, scheme="crank_nicolson").validate()
    assert not w, [str(x.message) for x in w]


# ---------------------------------------------------------------------------
# Accuracy
# ---------------------------------------------------------------------------

def test_backward_euler_tracks_explicit_at_same_dt():
    base = dict(nx=34, ny=34, cx=0.1, cy=0.1, steps=200, backend="jnp")
    ge = _solve_grid(HeatConfig(**base))
    gi = _solve_grid(HeatConfig(scheme="backward_euler", **base))
    scale = float(np.max(np.abs(ge)))
    assert np.all(np.isfinite(gi))
    # The schemes differ at O(dt): small relative to the field.
    assert float(np.max(np.abs(ge - gi))) < 5e-3 * scale


def test_implicit_100x_dt_finite_and_close_where_explicit_diverges():
    # 100x the explicit-stable step: explicit blows up to inf at this
    # coefficient sum; backward Euler completes and lands near the
    # explicit reference run at 100x more, stable, steps to the same
    # physical time. The bound here is 3e-2 of the problem scale: at
    # 34^2 one implicit step covers far more diffusion time relative
    # to the grid than at the bench row's 512^2 (where the documented
    # 1e-2 tolerance is met at ~2.6e-4 —
    # BENCH_r15_implicit_dryrun.json), so the first-order damping
    # error is proportionally larger.
    ref = _solve_grid(HeatConfig(nx=34, ny=34, cx=0.2, cy=0.2,
                                 steps=1000, backend="jnp"))
    gi = _solve_grid(HeatConfig(nx=34, ny=34, cx=20.0, cy=20.0,
                                steps=10, backend="jnp",
                                scheme="backward_euler"))
    assert np.all(np.isfinite(gi))
    scale = float(np.max(np.abs(
        _solve_grid(HeatConfig(nx=34, ny=34, steps=0)))))
    assert float(np.max(np.abs(ref - gi))) < 3e-2 * scale
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # intentional instability
        # 20 steps: the highest mode amplifies ~159x/step, so the
        # explicit run provably overflows f32 well within the window
        # (10 steps would still be finite — ~1e27 of headroom).
        diverged = solve(HeatConfig(nx=34, ny=34, cx=20.0, cy=20.0,
                                    steps=20, backend="jnp",
                                    guard_interval=20))
    assert diverged.finite is False  # the explicit run at this dt


def test_crank_nicolson_beats_backward_euler_at_large_dt():
    # Second vs first order: against a fine-dt explicit reference,
    # CN's error at a 50x step is strictly below BE's.
    ref = _solve_grid(HeatConfig(nx=26, ny=26, cx=0.2, cy=0.2,
                                 steps=500, backend="jnp"))
    big = dict(nx=26, ny=26, cx=10.0, cy=10.0, steps=10,
               backend="jnp")
    be = _solve_grid(HeatConfig(scheme="backward_euler", **big))
    cn = _solve_grid(HeatConfig(scheme="crank_nicolson", **big))
    err_be = float(np.max(np.abs(ref - be)))
    err_cn = float(np.max(np.abs(ref - cn)))
    assert err_cn < err_be


def test_converge_mode_drives_implicit_steps():
    # The converge-mode residual machinery transfers unchanged: an
    # implicit run reaches eps (in a handful of giant steps) and
    # reports converged with steps_run < budget.
    cfg = HeatConfig(nx=26, ny=26, cx=50.0, cy=50.0, steps=400,
                     converge=True, check_interval=4, eps=1e-2,
                     backend="jnp", scheme="backward_euler")
    r = solve(cfg)
    assert r.converged is True
    assert 0 < r.steps_run < 400
    assert r.residual is not None and r.residual < 1e-2


# ---------------------------------------------------------------------------
# Bitwise pins
# ---------------------------------------------------------------------------

def test_bitwise_reproducible_run_to_run():
    cfg = HeatConfig(nx=34, ny=34, cx=12.5, cy=12.5, steps=6,
                     backend="jnp", scheme="backward_euler")
    a = _solve_grid(cfg)
    b = _solve_grid(cfg)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("mesh", [(2, 4), (4, 2)])
def test_sharded_bitwise_identical_to_single_device(mesh):
    # THE multi-chip pin: the same implicit spec on the 8-device CPU
    # mesh is bitwise the single-device run — GSPMD partitions the
    # V-cycle (every reduction is the exactly-associative max).
    base = dict(nx=32, ny=32, cx=12.5, cy=12.5, steps=4,
                backend="jnp", scheme="backward_euler")
    solo = _solve_grid(HeatConfig(**base))
    sharded = _solve_grid(HeatConfig(mesh_shape=mesh, **base))
    np.testing.assert_array_equal(solo, sharded)


@pytest.mark.slow
def test_sharded_bitwise_converge_and_cn():
    # The heavier parity surface (converge-mode while_loop + CN RHS
    # over the mesh) — slow-marked per the tier-1 wall budget.
    for scheme in ("backward_euler", "crank_nicolson"):
        base = dict(nx=64, ny=64, cx=25.0, cy=25.0, steps=120,
                    converge=True, check_interval=4, eps=1e-3,
                    backend="jnp", scheme=scheme)
        solo = solve(HeatConfig(**base))
        sharded = solve(HeatConfig(mesh_shape=(2, 4), **base))
        assert solo.steps_run == sharded.steps_run
        assert solo.residual == sharded.residual
        np.testing.assert_array_equal(solo.to_numpy(),
                                      sharded.to_numpy())


def test_stream_chunked_bitwise_matches_one_shot():
    cfg = HeatConfig(nx=26, ny=26, cx=12.5, cy=12.5, steps=9,
                     backend="jnp", scheme="backward_euler")
    one = _solve_grid(cfg)
    last = None
    for last in solve_stream(cfg, chunk_steps=2):
        pass
    np.testing.assert_array_equal(one, last.to_numpy())
    assert last.steps_run == 9


def test_observer_toggles_zero_new_runner_misses_and_zero_bit_drift():
    # Acceptance pin: guard/diag/pipeline flips on an implicit config
    # reuse the plain run's compiled programs (no new _build_runner
    # misses) and move no bits.
    from parallel_heat_tpu import solver

    cfg = HeatConfig(nx=26, ny=26, cx=12.5, cy=12.5, steps=9,
                     backend="jnp", scheme="backward_euler")
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=3)]
    misses = solver._build_runner.cache_info().misses
    observed = [r.to_numpy() for r in solve_stream(
        cfg.replace(guard_interval=3, diag_interval=3),
        chunk_steps=3)]
    piped = [r.to_numpy() for r in solve_stream(
        cfg.replace(pipeline_depth=2, converge=False), chunk_steps=3)]
    assert solver._build_runner.cache_info().misses == misses
    for a, b in zip(plain, observed):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(plain, piped):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Pallas transfer kernels
# ---------------------------------------------------------------------------

def test_pallas_transfer_kernels_bitwise_jnp_spelling():
    from parallel_heat_tpu.ops import multigrid as mg

    rng = np.random.RandomState(7)
    fine = jnp.asarray(np.pad(
        rng.randn(32, 32).astype(np.float32), 1))
    coarse_shape = multigrid_level_shapes((34, 34))[1]
    r_jnp = mg.restrict_full_weighting(fine, coarse_shape)
    r_pl = mg._build_restrict_kernel((34, 34), tuple(coarse_shape))(fine)
    np.testing.assert_array_equal(np.asarray(r_jnp), np.asarray(r_pl))
    p_jnp = mg.prolong_bilinear(r_jnp, (32, 32))
    p_pl = mg._build_prolong_kernel(tuple(coarse_shape), (34, 34))(r_jnp)
    np.testing.assert_array_equal(np.asarray(p_jnp), np.asarray(p_pl))
    # Boundary ring of the prolonged correction is exactly zero (what
    # keeps boundary bits exact through the correction add).
    p = np.asarray(p_pl)
    assert not p[0].any() and not p[-1].any()
    assert not p[:, 0].any() and not p[:, -1].any()


def test_pallas_backend_implicit_solve_matches_jnp():
    # Off-TPU the transfer kernels run interpreted and are bitwise the
    # jnp spelling, so the whole pallas-backend implicit solve equals
    # the jnp backend's exactly — and explain reports the kernel pick.
    cfg = dict(nx=34, ny=34, cx=12.5, cy=12.5, steps=4,
               scheme="backward_euler")
    gj = _solve_grid(HeatConfig(backend="jnp", **cfg))
    gp = _solve_grid(HeatConfig(backend="pallas", **cfg))
    np.testing.assert_array_equal(gj, gp)
    ex = explain(HeatConfig(backend="pallas", **cfg))
    assert "heat_mg_restrict" in ex["multigrid"]["transfers"]


# ---------------------------------------------------------------------------
# explain / ensemble / admission / telemetry
# ---------------------------------------------------------------------------

def test_explain_reports_hierarchy_and_smoother():
    cfg = HeatConfig(nx=34, ny=34, cx=12.5, cy=12.5, steps=4,
                     backend="jnp", scheme="backward_euler")
    ex = explain(cfg)
    assert ex["scheme"] == "backward_euler"
    mgx = ex["multigrid"]
    assert [tuple(lv["shape"]) for lv in mgx["levels"]] == \
        multigrid_level_shapes((34, 34))
    # Rediscretized coefficients: theta*c / 4^l.
    assert mgx["levels"][1]["cx"] == pytest.approx(12.5 / 4)
    assert mgx["theta"] == 1.0
    assert "weighted-Jacobi" in mgx["smoother"]
    assert "V-cycle" in ex["path"]
    assert explain(cfg.replace(scheme="crank_nicolson")
                   )["multigrid"]["theta"] == 0.5


def test_ensemble_batches_vcycles_bitwise_member_parity():
    from parallel_heat_tpu.ensemble.engine import (
        EnsembleSolver, ensemble_path, packable)

    cfg = HeatConfig(nx=20, ny=20, cx=12.5, cy=12.5, steps=4,
                     backend="jnp", scheme="backward_euler")
    assert ensemble_path(cfg) == "vmap"
    ok, reason = packable(cfg)
    assert ok and "V-cycle" in reason
    # pallas-backend implicit jobs run solo: the batched vmap path's
    # jnp transfer spelling has no pinned bitwise twin on hardware
    # (same backend discipline as the explicit packable arm).
    ok_p, reason_p = packable(cfg.replace(backend="pallas"))
    assert not ok_p and "solo" in reason_p
    solo = _solve_grid(cfg)
    res = EnsembleSolver(cfg, 3).solve()
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(res.grids[i]), solo)


def test_admission_prices_level_hierarchy():
    from parallel_heat_tpu.service.admission import (
        estimate_job_hbm_bytes)

    base = {"nx": 512, "ny": 512}
    exp = estimate_job_hbm_bytes(base)
    imp = estimate_job_hbm_bytes({**base, "scheme": "backward_euler"})
    extra = sum(mx * my * 4 * 3
                for mx, my in multigrid_level_shapes((512, 512)))
    assert imp == exp + extra
    # mg_levels caps the priced hierarchy exactly like the solve's.
    capped = estimate_job_hbm_bytes(
        {**base, "scheme": "backward_euler", "mg_levels": 2})
    extra2 = sum(mx * my * 4 * 3
                 for mx, my in multigrid_level_shapes((512, 512), 2))
    assert capped == exp + extra2


def test_heatd_accepts_and_serves_implicit_specs(tmp_path):
    # Serving end-to-end: an implicit spec is admitted (HBM priced
    # over the level hierarchy), solved by the worker, completed, and
    # the SECOND submission of the same spec is an exact cache hit
    # with zero dispatches — while the explicit spelling of the same
    # grid shares nothing with it (the cross-scheme wall).
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
    from parallel_heat_tpu.service.harness import inline_launcher
    from parallel_heat_tpu.service.store import JobSpec

    root = str(tmp_path / "q")
    spawns = []
    daemon = Heatd(HeatdConfig(root=root, slots=1,
                               launcher=inline_launcher(root, spawns),
                               requeue_backoff_base_s=0.0))
    try:
        cfg = {"nx": 16, "ny": 16, "steps": 12, "cx": 5.0, "cy": 5.0,
               "backend": "jnp", "scheme": "backward_euler"}

        def run(jid, config):
            daemon.store.spool_submit(JobSpec(
                job_id=jid, config=config, checkpoint_every=4))
            for _ in range(400):
                daemon.step()
                jobs, _ = daemon.store.replay()
                v = jobs.get(jid)
                if v is not None and v.terminal:
                    return v
            raise AssertionError(f"{jid} never reached terminal")

        cold = run("imp-cold", cfg)
        assert cold.state == "completed"
        warm = run("imp-warm", cfg)
        assert warm.state == "completed"
        assert "imp-warm" not in spawns  # served from cache, O(1)
        # The explicit spelling of the same grid must NOT be served
        # from the implicit donor (different trajectory family).
        exp = run("exp-cold", {**cfg, "cx": 0.1, "cy": 0.1,
                               "scheme": "explicit"})
        assert exp.state == "completed"
        assert "exp-cold" in spawns  # a real solve, not a cache serve
    finally:
        daemon.close()


def test_vcycle_telemetry_event_and_diagnostics(tmp_path):
    import json

    from parallel_heat_tpu.utils.telemetry import Telemetry

    cfg = HeatConfig(nx=26, ny=26, cx=12.5, cy=12.5, steps=6,
                     backend="jnp", scheme="backward_euler",
                     diag_interval=3)
    path = tmp_path / "m.jsonl"
    tel = Telemetry(str(path))
    last = None
    for last in solve_stream(cfg, chunk_steps=3, telemetry=tel,
                             pipeline_depth=1):
        pass
    tel.close()
    vc = last.diagnostics["vcycle"]
    assert vc["cycles"] >= 1
    assert vc["residuals"] and all(r >= 0 for r in vc["residuals"])
    assert vc["levels"] == len(multigrid_level_shapes((26, 26)))
    events = [json.loads(line) for line in path.read_text().splitlines()]
    vevents = [e for e in events if e.get("event") == "vcycle"]
    assert len(vevents) == 2  # one per diag boundary
    assert vevents[0]["cycles"] >= 1
    # The once-per-stream level wall shares ride the FIRST sample.
    shares = vevents[0]["level_wall_share"]
    assert abs(sum(shares.values()) - 1.0) < 0.01
    assert "level_wall_share" not in vevents[1]
    if vevents[0].get("contraction") is not None:
        assert 0 < vevents[0]["contraction"] < 1


def test_resume_command_carries_scheme_and_mg_flags():
    # A supervised implicit run's printed resume line must rebuild the
    # SAME integrator: without --scheme, the resumed run would be an
    # explicit solve at super-stability coefficients — a deterministic
    # blow-up (and at any coefficients a different trajectory,
    # breaking the resume-bitwise contract).
    from parallel_heat_tpu.supervisor import (
        SupervisorPolicy, _resume_command)

    cfg = HeatConfig(nx=64, ny=64, cx=22.5, cy=22.5, steps=400,
                     backend="jnp", scheme="backward_euler",
                     mg_tol=1e-4, mg_levels=3)
    line = _resume_command(cfg, "/tmp/ck", 400,
                           SupervisorPolicy(checkpoint_every=40))
    assert "--scheme backward_euler" in line
    assert "--mg-tol 0.0001" in line
    assert "--mg-levels 3" in line
    assert "--mg-cycles" not in line  # defaults stay off the line
    assert "--mg-partition" not in line  # "auto" is the default
    # A forced partition spelling is SEMANTIC — dropping it would let
    # the resumed run's auto resolution pick a different program.
    cfg_p = cfg.replace(mesh_shape=(2, 4),
                        mg_partition="partitioned")
    line_p = _resume_command(cfg_p, "/tmp/ck", 400,
                             SupervisorPolicy(checkpoint_every=40))
    assert "--mg-partition partitioned" in line_p
    # Explicit configs stay scheme-flag-free (the default).
    line_e = _resume_command(
        HeatConfig(nx=64, ny=64, steps=400, backend="jnp"),
        "/tmp/ck", 400, SupervisorPolicy(checkpoint_every=40))
    assert "--scheme" not in line_e and "--mg-" not in line_e


def test_cycle_trace_budget_is_the_solve_budget():
    # The trace runs the solve's OWN while_loop budget (mg_cycles),
    # not a silent smaller cap: a smoother-only hierarchy
    # (mg_levels=1) needs well over 16 cycles here, and the trace
    # must still report the true count and converged=True.
    from parallel_heat_tpu.ops import multigrid as mg
    from parallel_heat_tpu.solver import make_initial_grid

    cfg = HeatConfig(nx=18, ny=18, cx=12.5, cy=12.5, steps=1,
                     backend="jnp", scheme="backward_euler",
                     mg_levels=1, mg_cycles=500)
    tr = mg.cycle_trace(cfg, make_initial_grid(cfg))
    assert tr["converged"] is True
    assert 16 < tr["cycles"] <= 500
    assert len(tr["residuals"]) == tr["cycles"]
    assert tr["residual_last"] <= tr["tol"]
    # An explicit max_cycles is an instrumentation cap, honestly
    # reported as unconverged when it bites.
    capped = mg.cycle_trace(cfg, make_initial_grid(cfg), max_cycles=4)
    assert capped["cycles"] == 4 and capped["converged"] is False


def test_cycle_trace_converges_within_tol():
    from parallel_heat_tpu.ops import multigrid as mg
    from parallel_heat_tpu.solver import make_initial_grid

    cfg = HeatConfig(nx=34, ny=34, cx=12.5, cy=12.5, steps=4,
                     backend="jnp", scheme="backward_euler")
    tr = mg.cycle_trace(cfg, make_initial_grid(cfg))
    assert tr["converged"] is True
    assert tr["cycles"] <= cfg.mg_cycles
    assert tr["residual_last"] <= tr["tol"]
    # Residuals contract monotonically on this well-posed solve.
    assert tr["contraction"] is not None and tr["contraction"] < 0.5


# ---------------------------------------------------------------------------
# Partitioned V-cycle (ops/multigrid_sharded.py; SEMANTICS.md
# "Partitioned V-cycle")
# ---------------------------------------------------------------------------

def _ms():
    from parallel_heat_tpu.ops import multigrid_sharded
    return multigrid_sharded


@pytest.mark.parametrize("scheme", ["backward_euler", "crank_nicolson"])
def test_partitioned_bitwise_identical_to_single_device(scheme):
    # THE partitioned pin: a one-level partitioned prefix (the floored
    # explicit plan at CPU-testable sizes) is BITWISE the
    # single-device run. Non-square geometry, so every coarse level
    # shape is mesh-indivisible and the padded-block layout is load-
    # bearing, not incidental.
    base = dict(nx=64, ny=32, cx=18.5, cy=11.5, steps=3,
                backend="jnp", scheme=scheme)
    solo = _solve_grid(HeatConfig(**base))
    part = _solve_grid(HeatConfig(mesh_shape=(2, 4),
                                  mg_partition="partitioned", **base))
    np.testing.assert_array_equal(solo, part)


@pytest.mark.slow
def test_partitioned_converge_bitwise():
    # Converge mode over the partitioned program: the pmax residual
    # verdict steers the same host control flow, so steps_run,
    # residual and the grid are all bitwise the single-device run.
    base = dict(nx=64, ny=64, cx=25.0, cy=25.0, steps=60,
                converge=True, check_interval=4, eps=1e-3,
                backend="jnp", scheme="backward_euler")
    solo = solve(HeatConfig(**base))
    part = solve(HeatConfig(mesh_shape=(2, 4),
                            mg_partition="partitioned", **base))
    assert solo.steps_run == part.steps_run
    assert solo.residual == part.residual
    np.testing.assert_array_equal(solo.to_numpy(), part.to_numpy())


def test_partitioned_deep_chain_allclose_contract(monkeypatch):
    # The documented parity BOUNDARY: with two+ partitioned levels the
    # REPLICATED reference itself recomputes its level-1 smooth chain
    # in fusion clusters whose FMA contraction differs (its fused
    # u1 + prolong(e2) stops matching the sum of its own materialized
    # operands on XLA:CPU), so deep chains are pinned allclose at
    # rtol 1e-6 (~100x the observed 1-ulp fork); the TPU re-run
    # protocol lives in the bench artifact. The block programs stay
    # self-consistent; the one-level prefix above stays bitwise.
    ms = _ms()
    monkeypatch.setattr(ms, "_MIN_PARTITIONED_FLOOR", 3)
    base = dict(nx=64, ny=64, cx=21.25, cy=21.25, steps=2,
                backend="jnp", scheme="backward_euler")
    solo = _solve_grid(HeatConfig(**base))
    part = _solve_grid(HeatConfig(mesh_shape=(2, 4),
                                  mg_partition="partitioned", **base))
    np.testing.assert_allclose(part, solo, rtol=1e-6)


@pytest.mark.slow
def test_partitioned_fully_partitioned_chain_allclose(monkeypatch):
    # No agglomeration at all (floor beyond the hierarchy): every
    # level runs as shard blocks, Crank-Nicolson RHS included.
    ms = _ms()
    monkeypatch.setattr(ms, "_MIN_PARTITIONED_FLOOR", 99)
    for scheme in ("backward_euler", "crank_nicolson"):
        base = dict(nx=64, ny=32, cx=20.5, cy=10.25, steps=3,
                    backend="jnp", scheme=scheme)
        solo = _solve_grid(HeatConfig(**base))
        part = _solve_grid(HeatConfig(mesh_shape=(2, 4),
                                      mg_partition="partitioned",
                                      **base))
        np.testing.assert_allclose(part, solo, rtol=1e-6)


def test_partition_plan_threshold_boundary_and_floor():
    # Host-arithmetic invariants of the agglomeration plan.
    ms = _ms()
    small = HeatConfig(nx=64, ny=64, cx=22.5, cy=22.5, steps=1,
                       scheme="backward_euler",
                       mesh_shape=(2, 4)).validate()
    plan = ms.partition_plan(small)
    # At CPU-testable sizes the v5e collective latency outprices the
    # saved compute on every level: analytic verdict is replicated.
    assert plan["auto_wins"] is False
    assert plan["partitioned_levels"] == 0
    assert all(lv["partition"] == "replicated" for lv in plan["levels"])
    assert plan["threshold"]["t_sweep_partitioned_s"] > \
        plan["threshold"]["t_sweep_replicated_s"]
    # The explicit-request floor: at least one level partitions, the
    # analytic verdict is preserved alongside.
    forced = ms.partition_plan(small, min_partitioned=1)
    assert forced["partitioned_levels"] == 1
    assert forced["analytic_partitioned_levels"] == 0
    assert forced["auto_wins"] is False
    assert forced["levels"][0]["partition"] == "partitioned"
    assert forced["levels"][1]["partition"] == "agglomerated"
    # Padded chain: each partitioned level's padded extent doubles the
    # next coarser one and covers the authentic shape.
    for fine, coarse in zip(forced["levels"], forced["levels"][1:]):
        if coarse.get("padded_shape") and fine.get("padded_shape"):
            assert tuple(fine["padded_shape"]) == tuple(
                2 * n for n in coarse["padded_shape"])
        if fine.get("padded_shape"):
            assert all(p >= s and p % d == 0 for p, s, d in zip(
                fine["padded_shape"], fine["shape"], (2, 4)))
    # Large grids flip the analytic verdict (monotone prefix).
    big = HeatConfig(nx=4096, ny=4096, cx=1400.0, cy=1400.0, steps=1,
                     scheme="backward_euler",
                     mesh_shape=(2, 4)).validate()
    bplan = ms.partition_plan(big)
    assert bplan["auto_wins"] is True
    assert bplan["partitioned_levels"] == 2
    kinds = [lv["partition"] for lv in bplan["levels"]]
    assert kinds[:2] == ["partitioned", "partitioned"]
    assert all(k == "agglomerated" for k in kinds[2:])
    assert ms.resolve_mg_partition(big) == "partitioned"
    assert ms.resolve_mg_partition(small) == "replicated"


def test_mg_partition_resolution_order_and_validation():
    # forced > tuned-db > analytic; the field is SEMANTIC (HL101) and
    # inert-knob-validated like the other mg_* flags.
    from parallel_heat_tpu import tune
    from parallel_heat_tpu.config import SEMANTIC_FIELDS

    ms = _ms()
    assert "mg_partition" in SEMANTIC_FIELDS
    small = HeatConfig(nx=64, ny=64, cx=22.5, cy=22.5, steps=1,
                       scheme="backward_euler",
                       mesh_shape=(2, 4)).validate()
    with tune.force("mg_partition", "partitioned"):
        assert ms.resolve_mg_partition(small) == "partitioned"
    with tune.force("mg_partition", "replicated"):
        assert ms.resolve_mg_partition(small) == "replicated"
    # Explicit values win over everything.
    with tune.force("mg_partition", "replicated"):
        assert ms.resolve_mg_partition(
            small.replace(mg_partition="partitioned")) == "partitioned"
    # Vocabulary and inert-knob rejections.
    with pytest.raises(ValueError, match="mg_partition"):
        HeatConfig(nx=16, ny=16, steps=1, scheme="backward_euler",
                   mesh_shape=(2, 2),
                   mg_partition="sideways").validate()
    with pytest.raises(ValueError, match="mg_partition"):
        HeatConfig(nx=16, ny=16, steps=1,
                   mg_partition="partitioned").validate()  # explicit
    with pytest.raises(ValueError, match="mg_partition"):
        HeatConfig(nx=16, ny=16, steps=1, scheme="backward_euler",
                   mg_partition="partitioned").validate()  # unsharded


def test_partitioned_stream_chunked_bitwise_matches_one_shot():
    cfg = HeatConfig(nx=32, ny=16, cx=11.5, cy=5.5, steps=9,
                     backend="jnp", scheme="backward_euler",
                     mesh_shape=(2, 4), mg_partition="partitioned")
    one = _solve_grid(cfg)
    last = None
    for last in solve_stream(cfg, chunk_steps=2):
        pass
    np.testing.assert_array_equal(one, last.to_numpy())
    assert last.steps_run == 9


def test_partitioned_observer_toggles_zero_new_runner_misses():
    # Observation-only flips on a PARTITIONED config reuse the
    # compiled shard_map programs (no new _build_runner misses) and
    # move no bits — mg_partition partitions into SEMANTIC_FIELDS,
    # the observers stay out of the memo key.
    from parallel_heat_tpu import solver

    cfg = HeatConfig(nx=32, ny=16, cx=11.25, cy=5.25, steps=6,
                     backend="jnp", scheme="backward_euler",
                     mesh_shape=(2, 4), mg_partition="partitioned")
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=3)]
    misses = solver._build_runner.cache_info().misses
    observed = [r.to_numpy() for r in solve_stream(
        cfg.replace(guard_interval=3, diag_interval=3),
        chunk_steps=3)]
    assert solver._build_runner.cache_info().misses == misses
    for a, b in zip(plain, observed):
        np.testing.assert_array_equal(a, b)


def test_partitioned_elastic_resume_reshard_on_load(tmp_path):
    # PR-10 elastic recovery through the partitioned program: a
    # checkpoint from a partitioned sharded run resumes onto a single
    # device, onto the replicated spelling, and back onto the
    # partitioned one — all bitwise an uninterrupted solo run.
    from parallel_heat_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint)

    base = dict(nx=32, ny=16, cx=11.75, cy=5.75, backend="jnp",
                scheme="backward_euler")
    mid = solve(HeatConfig(steps=10, mesh_shape=(2, 4),
                           mg_partition="partitioned", **base))
    p = tmp_path / "mgpart.npz"
    save_checkpoint(p, mid.to_numpy(), 10,
                    HeatConfig(steps=10, **base))
    grid, step, _ = load_checkpoint(p)
    assert step == 10
    want = solve(HeatConfig(steps=20, **base)).to_numpy()
    for kw in (dict(),
               dict(mesh_shape=(2, 4), mg_partition="replicated"),
               dict(mesh_shape=(2, 4), mg_partition="partitioned")):
        rest = solve(HeatConfig(steps=10, **base, **kw), initial=grid)
        np.testing.assert_array_equal(rest.to_numpy(), want,
                                      err_msg=f"resume {kw}")


def test_transfer_ops_agglomerated_pallas_selection():
    # Satellite bugfix pin: the Pallas transfer kernels decline on the
    # REPLICATED sharded path (GSPMD cannot partition a pallas_call)
    # but are admissible again on the agglomerated coarse levels of
    # the partitioned V-cycle, which run per-device inside shard_map.
    from parallel_heat_tpu.ops.multigrid import transfer_ops

    solo = HeatConfig(nx=34, ny=34, cx=12.5, cy=12.5, steps=1,
                      backend="pallas",
                      scheme="backward_euler").validate()
    sharded = HeatConfig(nx=32, ny=32, cx=12.5, cy=12.5, steps=1,
                         backend="pallas", scheme="backward_euler",
                         mesh_shape=(2, 4)).validate()

    def is_pallas(ops):
        return ops[0].__name__ == "restrict"

    assert is_pallas(transfer_ops(solo, "pallas"))
    assert not is_pallas(transfer_ops(sharded, "pallas"))
    assert is_pallas(transfer_ops(sharded, "pallas",
                                  agglomerated=True))
    assert not is_pallas(transfer_ops(sharded, "jnp",
                                      agglomerated=True))


def test_partitioned_pallas_backend_matches_jnp():
    # The agglomerated subtree serves the pallas transfer kernels
    # through the REAL partitioned path; interpreted off-TPU they are
    # bitwise the jnp spelling, so the whole solve matches exactly.
    base = dict(nx=32, ny=32, cx=12.25, cy=12.25, steps=2,
                scheme="backward_euler", mesh_shape=(2, 4),
                mg_partition="partitioned")
    a = _solve_grid(HeatConfig(backend="jnp", **base))
    b = _solve_grid(HeatConfig(backend="pallas", **base))
    np.testing.assert_array_equal(a, b)


def test_partitioned_explain_reports_plan_and_decided_by():
    base = dict(nx=64, ny=64, cx=22.5, cy=22.5, steps=3,
                backend="jnp", scheme="backward_euler",
                mesh_shape=(2, 4))
    ex = explain(HeatConfig(mg_partition="partitioned", **base))
    assert "partitioned multigrid V-cycle" in ex["path"]
    plan = ex["multigrid"]["partition_plan"]
    assert plan["mode"] == "partitioned"
    assert plan["partitioned_levels"] == 1
    assert plan["agglomerate_from"] == 1
    kinds = [lv["partition"] for lv in plan["levels"]]
    assert kinds[0] == "partitioned"
    assert all(k == "agglomerated" for k in kinds[1:])
    assert plan["threshold"] is not None
    assert "partitioned full-weighting" in ex["multigrid"]["transfers"]
    # auto on a small grid: analytic model decides replicated, and
    # explain says who decided.
    ex2 = explain(HeatConfig(**base))
    assert ex2["mg_partition"] == "replicated"
    assert ex2["decided_by"]["mg_partition"]["source"] == \
        "analytic-model"
    assert ex2["decided_by"]["mg_partition"]["choice"] == "replicated"
    # forced pin surfaces as the decider through the same recorder.
    from parallel_heat_tpu import tune
    with tune.force("mg_partition", "partitioned"):
        ex3 = explain(HeatConfig(**base))
    assert ex3["decided_by"]["mg_partition"]["source"] == "forced"
    assert "partition_plan" in ex3["multigrid"]
