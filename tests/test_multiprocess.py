"""True multi-process distributed runs (the multi-host/DCN analog).

Everything else in the suite validates sharding within one process
(8 virtual devices, one JAX runtime). These tests start TWO separate
Python processes that form one 8-device global mesh through
``jax.distributed.initialize`` — the same coordination-service path a
real multi-host TPU pod uses over DCN (SURVEY.md §2c: the reference's
analog is MPI ranks across lab machines). Each process owns 4 CPU
devices; the sharded solve spans both, so the halo ``ppermute``s, the
``pmax`` convergence vote, and the ``process_allgather`` in
``gather_to_host`` all cross a process boundary.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    # Older jax CPU backends only run cross-process collectives over
    # gloo; newer ones pick a working implementation themselves.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
from parallel_heat_tpu.utils.compat import request_cpu_devices
request_cpu_devices(4)
pid = int(sys.argv[1]); port = sys.argv[2]
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=2, process_id=pid)
sys.path.insert(0, {repo!r})
import numpy as np
from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.parallel.distributed import gather_to_host

assert len(jax.devices()) == 8, jax.devices()
kw = dict(nx=64, ny=64, steps=30, converge=True, check_interval=10,
          backend="jnp")
res = solve(HeatConfig(**kw, mesh_shape=(2, 4)))
full = np.asarray(gather_to_host(res.grid))
oracle = solve(HeatConfig(**kw)).to_numpy()
assert res.steps_run == 30
assert np.array_equal(full, oracle), "multi-process != single-device"

# K-deep temporal exchange (one collective round per 5 steps) across
# the same cross-process mesh must also match bitwise.
deep = solve(HeatConfig(**kw, mesh_shape=(2, 4), halo_depth=5))
assert np.array_equal(np.asarray(gather_to_host(deep.grid)), oracle), \\
    "multi-process deep-halo != single-device"

# Kernel G (fused assembly, interpret mode on CPU) across the process
# boundary: the K-deep exchange's ppermutes cross DCN coordination and
# the Mosaic round must still match the oracle to stencil-reassociation
# tolerance (the factored kernel algebra is deliberately not bitwise
# against the jnp tree).
from parallel_heat_tpu.ops import pallas_stencil as _ps
from parallel_heat_tpu.parallel.mesh import AXIS_NAMES as _AX

pal_cfg = HeatConfig(**kw, mesh_shape=(2, 4),
                     halo_depth=8).replace(backend="pallas")
kind, _, _ = _ps.pick_block_temporal_2d(pal_cfg, _AX[:2])
assert kind in ("G-uni", "G-fuse"), f"expected the Mosaic round, got {{kind}}"
pal = solve(pal_cfg)
assert pal.steps_run == 30
np.testing.assert_allclose(
    np.asarray(gather_to_host(pal.grid), dtype=np.float64),
    oracle.astype(np.float64), rtol=1e-4, atol=1e-2)

# Kernel H overlapped round across the process boundary: with a REAL
# process_count == 2 the deferred-x band split engages (the DCN gate
# that monkeypatched single-process tests can only simulate), so the
# bulk Mosaic call runs with no data from the x-phase ppermutes and
# the band kernel splices them in.
from parallel_heat_tpu.solver import explain as _explain

cfg3 = HeatConfig(nx=32, ny=16, nz=16, steps=8, mesh_shape=(2, 2, 2),
                  halo_depth=4).replace(backend="pallas")
p3 = _explain(cfg3)["path"]
assert "deferred x bands" in p3, f"expected the overlapped round, got {{p3}}"
res3 = solve(cfg3)
oracle3 = solve(HeatConfig(nx=32, ny=16, nz=16, steps=8)).to_numpy()
np.testing.assert_allclose(
    np.asarray(gather_to_host(res3.grid), dtype=np.float64),
    oracle3.astype(np.float64), rtol=1e-4, atol=1e-2)
# Save the gathered deferred-x result for the parent's bitwise check
# against the SAME schedule run in one process (monkeypatched DCN
# gate): the process boundary must change transport, never bits.
# gather_to_host is a collective — BOTH processes must call it; only
# p0 writes the file.
_g3 = np.asarray(gather_to_host(res3.grid))
if pid == 0:
    np.save("mp_h_deferred.npy", _g3)

# Per-shard checkpoint round trip across the process boundary: each
# process writes only its own shards (no host gather), p0 writes the
# manifest, and the fast-path load rebuilds the same sharded array.
from parallel_heat_tpu.utils.checkpoint import (load_checkpoint,
                                                save_checkpoint)

cfg = HeatConfig(**kw, mesh_shape=(2, 4))
d = save_checkpoint("mp_ck", deep.grid, deep.steps_run, cfg,
                    layout="sharded")
grid, step, _ = load_checkpoint(d, cfg)
assert step == deep.steps_run
assert not isinstance(grid, np.ndarray), "fast path must stay sharded"
assert np.array_equal(np.asarray(gather_to_host(grid)), oracle), \\
    "sharded checkpoint round trip != single-device"
print("WORKER-OK", pid, flush=True)
"""


_WORKER_STATIC = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
from parallel_heat_tpu.utils.compat import request_cpu_devices
request_cpu_devices(4)
pid = int(sys.argv[1]); port = sys.argv[2]
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=2, process_id=pid)
import numpy as np
from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.parallel.distributed import gather_to_host

assert len(jax.devices()) == 8, jax.devices()
kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4))

# Dynamic side: the halo exchange crosses a REAL process boundary and
# must reproduce the single-device oracle bitwise.
res = solve(HeatConfig(steps=12, **kw))
oracle = solve(HeatConfig(nx=32, ny=32, backend="jnp",
                          steps=12)).to_numpy()
got = np.asarray(gather_to_host(res.grid))
assert np.array_equal(got, oracle), "dynamic boundary parity failed"

# Static side: HL301 (+302/303) over the SAME (2, 4) topology, traced
# on the same 2-process global mesh — abstract evaluation only. The
# simulated-mesh verdict (exchange protocol provably correct) and the
# dynamic parity above are two proofs of one contract; a protocol bug
# would fail BOTH, a tracing/topology regression would split them.
from parallel_heat_tpu.analysis.spmd import _runner_target, audit_spmd

targets = [
    _runner_target(HeatConfig(steps=12, **kw), "mp-2x4", "fixed"),
    _runner_target(HeatConfig(steps=40, converge=True,
                              check_interval=10, **kw),
                   "mp-2x4", "converge"),
]
findings = audit_spmd(targets=targets)
assert findings == [], [f.message for f in findings]
print("WORKER-STATIC-OK", pid, flush=True)
"""


_WORKER_OVERLAP = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
from parallel_heat_tpu.utils.compat import request_cpu_devices
request_cpu_devices(4)
pid = int(sys.argv[1]); port = sys.argv[2]
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=2, process_id=pid)
import numpy as np
from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.parallel.distributed import gather_to_host

assert len(jax.devices()) == 8, jax.devices()

# Overlapped vs phase-separated jnp deep rounds across a REAL gloo
# boundary: the deferred phase-2 ppermutes cross DCN and must deliver
# byte-identical halos — both schedules bitwise the single-device
# oracle, on fixed (with a remainder round) AND converge modes.
kw = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 4), halo_depth=5)
for mode_kw in (dict(steps=23),
                dict(steps=400, converge=True, check_interval=10,
                     eps=1e-6)):
    oracle = solve(HeatConfig(nx=32, ny=32, backend="jnp",
                              **mode_kw)).to_numpy()
    ph = solve(HeatConfig(**kw, halo_overlap="phase", **mode_kw))
    ov = solve(HeatConfig(**kw, halo_overlap="overlap", **mode_kw))
    assert ph.steps_run == ov.steps_run
    assert np.array_equal(np.asarray(gather_to_host(ph.grid)), oracle)
    assert np.array_equal(np.asarray(gather_to_host(ov.grid)), oracle)

# Kernel-G pipelined (double-buffered edge strip) round across the
# boundary: round r+1's exchange operands — band/panel outputs — ride
# gloo while round r's bulk computes; must be bitwise the
# phase-separated Mosaic round and match the oracle to the usual
# stencil-reassociation tolerance.
pal = dict(nx=32, ny=32, steps=24, backend="pallas", mesh_shape=(2, 4),
           halo_depth=8)
pp = solve(HeatConfig(**pal, halo_overlap="pipeline"))
pg = solve(HeatConfig(**pal, halo_overlap="phase"))
got_pp = np.asarray(gather_to_host(pp.grid))
got_pg = np.asarray(gather_to_host(pg.grid))
assert np.array_equal(got_pp, got_pg), \\
    "pipelined != phase-separated across the process boundary"
oracle_p = solve(HeatConfig(nx=32, ny=32, steps=24)).to_numpy()
np.testing.assert_allclose(got_pp.astype(np.float64),
                           oracle_p.astype(np.float64),
                           rtol=1e-4, atol=1e-2)
print("WORKER-OVERLAP-OK", pid, flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(worker, port, env, tmp_path):
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(i), port],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env, cwd=str(tmp_path))
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_solve_matches_single_device(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo=REPO))
    env = dict(os.environ)
    # A parent JAX session must not leak its platform choice in.
    env.pop("JAX_PLATFORMS", None)
    # _free_port closes its probe socket before the coordinator binds
    # it (TOCTOU): another process can grab the port in between, so a
    # bind failure retries on a fresh port instead of flaking.
    for attempt in range(3):
        port = str(_free_port())
        procs, outs = _run_workers(worker, port, env, tmp_path)
        if attempt < 2 and any(p.returncode != 0 for p in procs) \
                and any("already in use" in o.lower()
                        or "address in use" in o.lower() for o in outs):
            continue
        break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER-OK {i}" in out

    # Kernel-H deferred-x band path, bitwise across the process
    # boundary: the worker ran the overlapped round under a REAL
    # process_count == 2 (the DCN gate); re-running the identical
    # config in THIS single process with the gate monkeypatched to 2
    # must reproduce it bit for bit — same mesh, same Mosaic kernels,
    # same deferred-x schedule, only the collective transport differs.
    import jax
    import pytest as _pytest

    from parallel_heat_tpu import HeatConfig, solve
    from parallel_heat_tpu.solver import _build_runner, explain

    got = np.load(tmp_path / "mp_h_deferred.npy")
    cfg3 = HeatConfig(nx=32, ny=16, nz=16, steps=8, mesh_shape=(2, 2, 2),
                      halo_depth=4).replace(backend="pallas")
    mp = _pytest.MonkeyPatch()
    try:
        mp.setattr(jax, "process_count", lambda: 2)
        # The runner cache must not serve a program built under the
        # real (single-process) gate.
        _build_runner.cache_clear()
        assert "deferred x bands" in explain(cfg3)["path"]
        ref = solve(cfg3).to_numpy()
    finally:
        mp.undo()
        _build_runner.cache_clear()
    assert np.array_equal(got, ref), \
        "kernel-H deferred-x: multi-process != single-process (bitwise)"


def _chaos_matrix():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_matrix", os.path.join(REPO, "tools", "chaos_matrix.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_mp_split_brain_consensus_rollback_bitwise(tmp_path):
    """The mp_split_brain chaos cell as a pytest case (also the `make
    mp-smoke` / CI gate): a single-rank NaN injected across a REAL
    2-process gloo boundary makes BOTH ranks trip at the same chunk
    boundary, roll back to the SAME generation, and recover bitwise —
    plus the 4-process-checkpoint -> 2-process elastic reshard-on-load
    resumed mid-cell. Marked slow: two jax.distributed runtimes cost
    tens of seconds, which the tier-1 870s budget cannot absorb; CI
    runs it in the mp-smoke job."""
    cm = _chaos_matrix()
    row = cm.run_mp_cell("mp_split_brain", str(tmp_path))
    assert row["outcome"] == "recovered", row
    assert row["consensus_trip_ok"] and row["bitwise_match"]
    assert row["same_rollback_generation_ok"]
    assert row["consensus_events_ok"] and row["elastic_4to2_ok"]


@pytest.mark.slow
def test_mp_peer_lost_bounded_detection_elastic_resume(tmp_path):
    """The mp_peer_lost chaos cell as a pytest case: rank 1 REALLY
    SIGKILLs itself mid-run; rank 0 must detect the corpse within one
    barrier timeout (no wedged ppermute), journal peer_lost, exit
    preempted with an elastic resume command targeting the surviving
    mesh — and executing that printed command verbatim completes the
    run bit-exactly. Slow-marked like the split-brain cell."""
    cm = _chaos_matrix()
    row = cm.run_mp_cell("mp_peer_lost", str(tmp_path))
    assert row["outcome"] == "recovered", row
    assert row["rank1_sigkilled_ok"] and row["rank0_ok"]
    assert row["detect_bounded_ok"] and row["peer_lost_event_ok"]
    assert row["elastic_cmd_ok"] and row["resume_exit_ok"]
    assert row["bitwise_match"] and row["resumed_steps"] == 60


@pytest.mark.slow
def test_two_process_overlap_schedules_bitwise(tmp_path):
    """Overlapped-exchange parity on a REAL 2-process gloo boundary
    (SEMANTICS.md "Overlapped exchange"): the deferred jnp rounds
    (fixed with remainder + converge) are bitwise the single-device
    oracle AND their phase-separated twins, and the kernel-G pipelined
    round is bitwise its phase-separated twin — the double-buffered
    exchange operands cross DCN and must deliver identical bytes.
    Marked slow (two jax.distributed runtimes — the tier-1 870s
    budget cannot absorb them); CI's mp-smoke job covers the same
    contract via the mp_overlap_parity chaos cell."""
    worker = tmp_path / "worker_overlap.py"
    worker.write_text(_WORKER_OVERLAP.format(repo=REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for attempt in range(3):
        port = str(_free_port())
        procs, outs = _run_workers(worker, port, env, tmp_path)
        if attempt < 2 and any(p.returncode != 0 for p in procs) \
                and any("already in use" in o.lower()
                        or "address in use" in o.lower() for o in outs):
            continue
        break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER-OVERLAP-OK {i}" in out


def test_two_process_static_proof_matches_dynamic_parity(tmp_path):
    """HL301's simulated-mesh verdict and the real-boundary exchange
    agree on the same (2, 4) topology: the workers run the dynamic
    bitwise parity AND the static SPMD audit over the identical
    2-process global mesh — the static proof covers exactly the
    programs the dynamic suite executes."""
    worker = tmp_path / "worker_static.py"
    worker.write_text(_WORKER_STATIC.format(repo=REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for attempt in range(3):
        port = str(_free_port())
        procs, outs = _run_workers(worker, port, env, tmp_path)
        if attempt < 2 and any(p.returncode != 0 for p in procs) \
                and any("already in use" in o.lower()
                        or "address in use" in o.lower() for o in outs):
            continue
        break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER-STATIC-OK {i}" in out
