"""Smoke tests for the tools/ scripts (they must not rot)."""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.parametrize("extra", [[], ["--halo-depth", "2"]])
def test_scaling_study_smoke(extra):
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "scaling_study.py"),
         "--cpu-devices", "4", "--sizes", "64", "--meshes", "1x1,2x2",
         "--steps", "20", "--repeats", "1", "--backend", "jnp"] + extra,
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    assert {r["mesh"] for r in rows} == {"1x1", "2x2"}
    assert all(r["wall_s"] > 0 for r in rows)
    assert "| mesh 2x2" in out.stdout  # the reference-style table


def test_bench_importable_and_baseline_set():
    sys.path.insert(0, _ROOT)
    try:
        import bench

        assert bench.BASELINE_MCELLS_PER_S > 0
        assert callable(bench.main)
    finally:
        sys.path.remove(_ROOT)


def test_ab_uni_single_smoke(tmp_path):
    # The windowed-vs-uniform A/B harness must run end to end (tiny
    # grid, interpret-mode kernels) and emit its JSON artifact with
    # rates for both kernel-E schedules.
    out_json = tmp_path / "ab_uni.json"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "ab_uni_single.py"),
         "--size", "64", "--json", str(out_json)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    row = doc["rows"]["64x64 float32"]
    assert "E (windowed)" in row["gcells_steps_per_s"]
    assert "E-uni (uniform gather)" in row["gcells_steps_per_s"]
    assert "pick_single_2d" in out.stdout


def test_headline_variance_row_specs():
    # The variance protocol's row table must stay in sync with
    # bench.py's stdout contract fields.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hv", os.path.join(_ROOT, "tools", "headline_variance.py"))
    hv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hv)
    assert set(hv._ROWS) == {"headline", "conv256"}
    assert hv._ROWS["conv256"]["field"] == "wall_s"
    assert hv._ROWS["headline"]["field"] == "value"


def test_make_heat_smoke():
    # The reference-style Make entry point must stay runnable.
    run = lambda *a: subprocess.run(
        ["make", "-C", _ROOT, *a], capture_output=True, text=True,
        timeout=300, env={**os.environ})
    out = run("heat", "SIZE=32", "STEPS=10", "BACKEND=jnp")
    assert out.returncode == 0, out.stderr[-2000:]
    assert os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    assert os.path.exists(os.path.join(_ROOT, "initial_im.dat"))
    out = run("clean")
    assert out.returncode == 0
    assert not os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    # clean also drops the native build; restore it so later suites
    # don't pay a rebuild
    assert run("native").returncode == 0


@pytest.mark.chaos
def test_chaos_matrix_dryrun_smoke(tmp_path):
    # The fault x policy sweep must run end to end on CPU and certify
    # its own contract (exit 0 == every bitwise/detection/halt check
    # held); the committed chaos_r7_dryrun.json is this exact run.
    out_json = tmp_path / "chaos.json"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "chaos_matrix.py"),
         "--dryrun", "--json", str(out_json)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    assert doc["ok"] is True
    outcomes = {r["fault"]: r["outcome"] for r in doc["rows"]}
    assert outcomes["nan_transient"] == "recovered"
    assert outcomes["nan_recurring"] == "halted"
    assert outcomes["unstable"] == "halted"
    assert outcomes["sigterm"] == "interrupted+resumed"
    assert all(r.get("bitwise_match", True) for r in doc["rows"])
