"""Smoke tests for the tools/ scripts (they must not rot)."""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.parametrize("extra", [[], ["--halo-depth", "2"]])
def test_scaling_study_smoke(extra):
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "scaling_study.py"),
         "--cpu-devices", "4", "--sizes", "64", "--meshes", "1x1,2x2",
         "--steps", "20", "--repeats", "1", "--backend", "jnp"] + extra,
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    assert {r["mesh"] for r in rows} == {"1x1", "2x2"}
    assert all(r["wall_s"] > 0 for r in rows)
    assert "| mesh 2x2" in out.stdout  # the reference-style table


def test_scaling_study_weak_mode_exchange_split(tmp_path):
    # Weak-scaling mode: fixed cells/device, schedule sweep, and the
    # exchange-wall vs compute-wall split per cell — the overlapped
    # schedule's critical-path exchange program carries HALF the
    # ppermute phases, so its exchange wall must come in strictly
    # below the phase-separated one (the structural claim
    # MULTICHIP_r06.json commits at artifact scale).
    out_json = tmp_path / "weak.json"
    metrics = tmp_path / "weak.jsonl"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "scaling_study.py"),
         "--cpu-devices", "8", "--weak", "--sizes", "24",
         "--meshes", "1x1,2x2", "--steps", "32", "--halo-depth", "4",
         "--repeats", "3", "--backend", "jnp",
         "--schedules", "phase,overlap",
         "--metrics", str(metrics), "--out", str(out_json)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    assert doc["mode"] == "weak"
    by = {(r["mesh"], r["schedule"]): r for r in doc["cells"]}
    assert set(by) == {("1x1", "phase"), ("1x1", "overlap"),
                       ("2x2", "phase"), ("2x2", "overlap")}
    for r in doc["cells"]:
        assert r["cells_per_device"] == 24 * 24
        assert r["compute_wall_s"] >= 0
        assert r["schedule_resolved"] == r["schedule"]
    # single-device rows have no exchange; sharded rows measured one
    assert by[("1x1", "phase")]["exchange_wall_s"] == 0
    assert by[("2x2", "phase")]["exchange_wall_s"] > 0
    assert by[("2x2", "overlap")]["exchange_wall_s"] > 0

    # The overlap-vs-phase claim is STRUCTURAL, so prove it on the
    # probes' traced programs rather than on two tiny CPU timings
    # (a strict wall-clock inequality here would be exactly the
    # load-sensitive flake the ab_uni smoke rewrite removed): the
    # overlapped critical path carries HALF the ppermutes.
    import importlib.util

    import jax

    spec = importlib.util.spec_from_file_location(
        "scaling_study", os.path.join(_ROOT, "tools",
                                      "scaling_study.py"))
    ss = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ss)
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.solver import make_initial_grid

    cfg = HeatConfig(nx=48, ny=48, steps=32, backend="jnp",
                     mesh_shape=(2, 2), halo_depth=4,
                     halo_overlap="overlap").validate()
    u0 = make_initial_grid(cfg)
    n_perm = {}
    for sched in ("phase", "overlap"):
        probe = ss._exchange_probe(cfg, sched, rounds=1)
        # Post-optimization HLO: the deferred phase's ppermutes have
        # no consumer in the overlap probe and are DCEd by XLA (trace
        # level still carries them), so the compiled critical path
        # provably holds fewer collective-permutes.
        txt = probe.lower(u0).compile().as_text()
        n_perm[sched] = txt.count("collective-permute")
    assert 0 < n_perm["overlap"] < n_perm["phase"], n_perm

    # metrics_report ingests the emitted chunk events and derives the
    # gateable exchange_share (shared --fail-on grammar)...
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(metrics), "--fail-on", "exchange_share>0.999", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert rep.returncode == 0, rep.stderr[-2000:]
    rdoc = json.loads(rep.stdout)
    assert 0 < rdoc["chunks"]["exchange_share"] < 1
    assert rdoc["chunks"]["exchange_s_total"] > 0
    # ...and a tight ceiling trips the anomaly exit (2)
    rep2 = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(metrics), "--fail-on", "exchange_share>0.0001"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert rep2.returncode == 2, rep2.stdout[-2000:]
    # slo_gate speaks the same grammar on the same stream
    for tok, rc in (("exchange_share>0.999", 0),
                    ("exchange_share>0.0001", 2)):
        g = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "slo_gate.py"),
             "--stream", tok, str(metrics)],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert g.returncode == rc, (tok, g.stdout, g.stderr)


def test_bench_importable_and_baseline_set():
    sys.path.insert(0, _ROOT)
    try:
        import bench

        assert bench.BASELINE_MCELLS_PER_S > 0
        assert callable(bench.main)
    finally:
        sys.path.remove(_ROOT)


def test_bench_stream_row_smoke():
    # The --row stream512 protocol at a toy size: one JSON line with
    # the bare/sync/pipelined walls and both overhead fractions — the
    # numbers the BENCH artifact records at real scale.
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--row", "stream512", "--backend", "jnp",
         "--stream-size", "64", "--stream-steps", "200",
         "--stream-chunk", "50"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    for k in ("wall_bare_s", "wall_sync_s", "wall_pipelined_s",
              "overhead_sync_frac", "overhead_pipelined_frac"):
        assert isinstance(row[k], float)
    assert row["wall_bare_s"] > 0


def test_ab_uni_single_smoke(tmp_path, monkeypatch, capsys):
    # The windowed-vs-uniform A/B harness must run end to end (tiny
    # grid, interpret-mode kernels: builders, warm calls, model
    # printout, artifact) and emit its JSON with rates for both
    # kernel-E schedules. The TIMING is driven by the deterministic
    # clock model test_aux uses (chain_time = floor + per*reps): the
    # real-clock subprocess variant failed identically on the
    # pristine tree under VM load (chain_slope correctly REFUSES a
    # noise-swamped slope — CHANGES round 16), so wall time here
    # would test the machine, not the tool.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ab_uni_single", os.path.join(_ROOT, "tools",
                                      "ab_uni_single.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)

    from parallel_heat_tpu.utils import measure

    # The protocol lives in utils/measure.py now (bench_rounds_paired
    # calls it there), so the stub targets the measure module and
    # absorbs the clock= plumbing kwarg.
    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.2 + 1e-3 * reps)
    out_json = tmp_path / "ab_uni.json"
    monkeypatch.setattr(sys, "argv",
                        ["ab_uni_single.py", "--size", "64",
                         "--json", str(out_json)])
    tool.main()
    doc = json.loads(out_json.read_text())
    row = doc["rows"]["64x64 float32"]
    assert "E (windowed)" in row["gcells_steps_per_s"]
    assert "E-uni (uniform gather)" in row["gcells_steps_per_s"]
    # Every variant saw the same fake per-call time, so the paired
    # protocol must report identical (finite) rates.
    rates = set(row["gcells_steps_per_s"].values())
    assert len(rates) == 1 and all(r > 0 for r in rates)
    assert "pick_single_2d" in capsys.readouterr().out


def test_headline_variance_row_specs():
    # The variance protocol's row table must stay in sync with
    # bench.py's stdout contract fields.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hv", os.path.join(_ROOT, "tools", "headline_variance.py"))
    hv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hv)
    assert set(hv._ROWS) == {"headline", "conv256"}
    assert hv._ROWS["conv256"]["field"] == "wall_s"
    assert hv._ROWS["headline"]["field"] == "value"


def test_make_heat_smoke():
    # The reference-style Make entry point must stay runnable.
    run = lambda *a: subprocess.run(
        ["make", "-C", _ROOT, *a], capture_output=True, text=True,
        timeout=300, env={**os.environ})
    out = run("heat", "SIZE=32", "STEPS=10", "BACKEND=jnp")
    assert out.returncode == 0, out.stderr[-2000:]
    assert os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    assert os.path.exists(os.path.join(_ROOT, "initial_im.dat"))
    out = run("clean")
    assert out.returncode == 0
    assert not os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    # clean also drops the native build; restore it so later suites
    # don't pay a rebuild
    assert run("native").returncode == 0


def test_metrics_report_round_trip(tmp_path):
    # CLI --metrics -> JSONL -> tools/metrics_report.py --json: the
    # full telemetry pipeline, as `make telemetry-smoke` drives it.
    m = tmp_path / "m.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    run = subprocess.run(
        [sys.executable, "-m", "parallel_heat_tpu", "--nx", "32",
         "--ny", "32", "--steps", "60", "--backend", "jnp",
         "--supervise", "--checkpoint", str(tmp_path / "ck"),
         "--checkpoint-every", "20", "--guard-interval", "10",
         "--metrics", str(m), "--quiet"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert run.returncode == 0, run.stderr[-2000:]
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--json"],
        capture_output=True, text=True, timeout=300, env=env)
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    assert doc["header"]["config"]["nx"] == 32
    assert doc["chunks"]["count"] == 6
    assert doc["chunks"]["steps_total"] == 60
    assert doc["chunks"]["steps_per_s"]["p50"] > 0
    assert doc["checkpoints"]["saves"] == 4
    assert 0 < doc["checkpoints"]["overhead_share"] <= 1
    assert doc["outcome"] == "complete" and doc["anomalies"] == []
    # the human-readable rendering works on the same stream
    txt = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(m)],
        capture_output=True, text=True, timeout=300, env=env)
    assert txt.returncode == 0 and "outcome: complete" in txt.stdout
    # anomaly thresholds drive the exit code (CI contract): a
    # checkpoint-share ceiling this tiny run must exceed -> exit 2
    bad = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--max-ckpt-share", "0.0000001"],
        capture_output=True, text=True, timeout=300, env=env)
    assert bad.returncode == 2 and "ANOMALY" in bad.stdout
    # unusable input is distinct from an anomaly -> exit 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    none = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(empty)],
        capture_output=True, text=True, timeout=300, env=env)
    assert none.returncode == 1


def test_monitor_once_round_trip(tmp_path):
    # CLI converge run with --metrics/--heartbeat/--diag-interval ->
    # tools/monitor.py --once must render step/throughput/residual
    # from the real artifacts (the `make monitor-smoke` pipeline), and
    # tools/metrics_report.py must produce the convergence section.
    m = tmp_path / "m.jsonl"
    hb = tmp_path / "hb.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    run = subprocess.run(
        [sys.executable, "-m", "parallel_heat_tpu", "--nx", "32",
         "--ny", "32", "--steps", "2000", "--converge", "--eps", "1e-3",
         "--check-interval", "20", "--backend", "jnp",
         "--diag-interval", "100", "--checkpoint", str(tmp_path / "ck"),
         "--checkpoint-every", "200", "--metrics", str(m),
         "--heartbeat", str(hb), "--monitor-hint"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert run.returncode == 0, run.stderr[-2000:]
    assert "Monitor with: python tools/monitor.py" in run.stdout
    mon = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "monitor.py"),
         "--once", "--heartbeat", str(hb), "--metrics", str(m)],
        capture_output=True, text=True, timeout=60, env=env)
    assert mon.returncode == 0, mon.stderr[-2000:]
    line = mon.stdout.strip()
    assert "step 2000/2000" in line
    assert "steps/s" in line
    assert "residual" in line
    assert "heat" in line
    assert "outcome complete" in line
    # heartbeat alone is enough for a liveness probe (no JSONL parse)
    mon_hb = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "monitor.py"),
         "--once", "--heartbeat", str(hb)],
        capture_output=True, text=True, timeout=60, env=env)
    assert mon_hb.returncode == 0
    assert "step 2000" in mon_hb.stdout
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--json"],
        capture_output=True, text=True, timeout=60, env=env)
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    cv = doc["convergence"]
    assert cv["residual_last"] < cv["residual_first"]
    assert cv["residual_slope_log10_per_kstep"] < 0  # healthy decay
    assert cv["diag_samples"] >= 3
    assert cv["heat_drift_max_frac"] >= 0
    assert cv["update_linf_last"] is not None


def _fake_stream_lines(n_chunks=4):
    """Hand-built telemetry stream (no simulation, no jax import):
    enough schema for metrics_report to summarize."""
    lines = [json.dumps({
        "schema": 1, "event": "run_header", "t_wall": 1.0, "t_mono": 1.0,
        "config": {"nx": 16, "ny": 16, "steps": 40, "dtype": "float32"},
        "explain": {"path": "XLA-fused jnp stencil"}})]
    for i in range(n_chunks):
        lines.append(json.dumps({
            "schema": 1, "event": "chunk", "t_wall": 2.0 + i,
            "t_mono": 2.0 + i, "step": 10 * (i + 1), "steps": 10,
            "wall_s": 0.01, "steps_per_s": 1000.0,
            "residual": 0.1 / (i + 1)}))
    return lines


def test_metrics_report_vcycle_section_and_gates(tmp_path):
    # The implicit-stepping V-cycle section (SEMANTICS.md "Implicit
    # stepping"): `vcycle` events -> cycles/step percentiles,
    # contraction factor, per-level wall shares — gateable through the
    # shared --fail-on grammar like every other section.
    m = tmp_path / "m.jsonl"
    events = [{"event": "run_header", "schema": 1,
               "config": {"nx": 26, "ny": 26,
                          "scheme": "backward_euler"}}]
    for step, cycles, contr in ((3, 3, 0.21), (6, 2, 0.18)):
        events.append({"event": "chunk", "schema": 1, "step": step,
                       "steps": 3, "wall_s": 0.01})
        ev = {"event": "vcycle", "schema": 1, "step": step,
              "cycles": cycles, "contraction": contr,
              "residuals": [1.0, 0.2], "tol": 0.5, "levels": 4,
              "converged": True}
        if step == 3:
            ev["level_wall_share"] = {"l0": 0.7, "l1": 0.2,
                                      "l2": 0.07, "l3": 0.03}
        events.append(ev)
    m.write_text("".join(json.dumps(e) + "\n" for e in events))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--json"],
        capture_output=True, text=True, timeout=300, env=env)
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    vc = doc["vcycle"]
    assert vc["samples"] == 2
    assert vc["cycles_per_step"]["max"] == 3
    assert vc["contraction"]["p50"] in (0.18, 0.21)
    assert vc["levels"] == 4
    assert vc["unconverged_samples"] == 0
    assert vc["level_wall_share"]["l0"] == 0.7
    # text rendering carries the section
    txt = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(m)],
        capture_output=True, text=True, timeout=300, env=env)
    assert txt.returncode == 0 and "vcycle:" in txt.stdout
    # the shared threshold grammar gates the section (exit 2), both a
    # cycles ceiling and a contraction ceiling
    for gate in ("vcycle.cycles_per_step.max>2",
                 "vcycle.contraction.p50>0.1"):
        bad = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "metrics_report.py"),
             str(m), "--fail-on", gate],
            capture_output=True, text=True, timeout=300, env=env)
        assert bad.returncode == 2, gate
        assert "ANOMALY" in bad.stdout
    # ...and passes at honest thresholds (exit 0)
    ok = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--fail-on",
         "permanent_failure,vcycle.cycles_per_step.p90>12,"
         "vcycle.contraction.p50>0.6"],
        capture_output=True, text=True, timeout=300, env=env)
    assert ok.returncode == 0, ok.stdout[-2000:]


def test_metrics_report_torn_final_line(tmp_path):
    # A mid-write reader sees a torn final line: the report must skip
    # it with a warning and summarize the intact prefix (exit 0), not
    # fail the whole report.
    m = tmp_path / "m.jsonl"
    full = "\n".join(_fake_stream_lines()) + "\n"
    torn = full + '{"schema": 1, "event": "chunk", "t_wall": 99.0, "t_m'
    m.write_text(torn)
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--json"],
        capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "torn final line" in rep.stderr
    doc = json.loads(rep.stdout)
    assert doc["torn_tail"] is True
    assert doc["bad_lines"] == 0  # a torn tail is not a corrupt line
    assert doc["chunks"]["count"] == 4  # the prefix summarized fully


def test_metrics_report_merges_shard_glob(tmp_path):
    # Multi-process runs shard per process (.pN.jsonl); a glob argument
    # reports across them: aggregates from the primary shard only
    # (SPMD processes emit EQUIVALENT streams — concatenating would
    # double-count steps and fabricate stall windows), all shards
    # listed with health flags.
    for pi in (0, 1):
        lines = [json.dumps({
            "schema": 1, "event": "run_header", "t_wall": 1.0,
            "t_mono": 1.0 + pi,
            "config": {"nx": 16, "ny": 16, "steps": 20},
            "process_index": pi, "process_count": 2})]
        lines.append(json.dumps({
            "schema": 1, "event": "chunk", "t_wall": 2.0,
            "t_mono": 2.0 + pi, "step": 20, "steps": 20,
            "wall_s": 0.01, "steps_per_s": 2000.0,
            "process_index": pi, "process_count": 2}))
        (tmp_path / f"m.p{pi}.jsonl").write_text("\n".join(lines) + "\n")
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(tmp_path / "m*.jsonl"), "--json"],
        capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    assert [s["process_index"] for s in doc["shards"]] == [0, 1]
    assert all(s["events"] == 2 and s["torn"] is False
               for s in doc["shards"])
    # primary-shard aggregates: no double counting across shards
    assert doc["chunks"]["count"] == 1
    assert doc["chunks"]["steps_total"] == 20
    assert doc["header"]["segments"] == 1


def test_metrics_report_per_rank_barrier_wait_row(tmp_path):
    # The distributed-supervision consensus exchanges (ISSUE 10) emit
    # per-boundary barrier_wait events; the shard-glob report must
    # render PER-RANK percentiles — unlike the SPMD-equivalent chunk
    # events, barrier waits differ by rank, and the rank that never
    # waits is the straggler everyone else waits for. peer_lost events
    # surface on the shard row too.
    for pi in (0, 1):
        lines = [json.dumps({
            "schema": 1, "event": "run_header", "t_wall": 1.0,
            "t_mono": 1.0, "config": {"nx": 16, "ny": 16, "steps": 30},
            "process_index": pi, "process_count": 2})]
        for k in range(3):
            lines.append(json.dumps({
                "schema": 1, "event": "chunk", "t_wall": 2.0 + k,
                "t_mono": 2.0 + k, "step": 10 * (k + 1), "steps": 10,
                "wall_s": 0.01, "process_index": pi,
                "process_count": 2}))
            lines.append(json.dumps({
                "schema": 1, "event": "barrier_wait",
                "t_wall": 2.1 + k, "t_mono": 2.1 + k,
                "step": 10 * (k + 1),
                "wait_s": 0.002 * (pi + 1) * (k + 1),
                "process_index": pi, "process_count": 2}))
        if pi == 0:
            lines.append(json.dumps({
                "schema": 1, "event": "peer_lost", "t_wall": 9.0,
                "t_mono": 9.0, "step": 30, "lost": [1],
                "survivors": 1, "waited_s": 1.2, "timeout_s": 5.0,
                "process_index": 0, "process_count": 2}))
        (tmp_path / f"m.p{pi}.jsonl").write_text("\n".join(lines) + "\n")
    run = lambda *a: subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(tmp_path / "m*.jsonl"), *a],
        capture_output=True, text=True, timeout=60)
    rep = run("--json")
    assert rep.returncode == 0, rep.stderr[-2000:]
    shards = json.loads(rep.stdout)["shards"]
    bw = {s["process_index"]: s["barrier_wait"] for s in shards}
    assert bw[0]["n"] == bw[1]["n"] == 3
    assert bw[0]["p50_s"] == pytest.approx(0.004)
    assert bw[1]["p50_s"] == pytest.approx(0.008)
    assert bw[1]["max_s"] == pytest.approx(0.012)
    assert {s["process_index"]: s["peer_lost"]
            for s in shards} == {0: 1, 1: 0}
    text = run()
    assert text.returncode == 0
    assert "barrier-wait p50=4.0ms" in text.stdout
    assert "PEER_LOST x1" in text.stdout


def _run_heatlint(*args, cwd=None):
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "heatlint.py"),
         *args],
        capture_output=True, text=True, timeout=300,
        cwd=cwd or _ROOT, env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_heatlint_sarif_round_trip(tmp_path):
    # Seed an AST violation, emit SARIF, and check the document is a
    # valid SARIF 2.1.0 skeleton whose results point at the finding —
    # the format CI uploads for PR annotation.
    (tmp_path / "seeded.py").write_text("import os\n")
    out = _run_heatlint("--layer", "ast", "--no-baseline",
                        "--format", "sarif", str(tmp_path))
    assert out.returncode == 2  # findings still gate in sarif mode
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "heatlint"
    results = run["results"]
    assert any(r["ruleId"] == "HL205" and r["level"] == "error"
               for r in results)
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {r["ruleId"] for r in results} <= rule_ids
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("seeded.py")
    assert loc["region"]["startLine"] >= 1
    # out-of-repo findings are self-contained absolute file URIs (a
    # SRCROOT-relative URI would resolve against the repo root and
    # point at nothing)
    assert loc["artifactLocation"]["uri"].startswith("file://")
    assert "uriBaseId" not in loc["artifactLocation"]
    # the clean tree emits an empty (but well-formed) run, and SRCROOT
    # names the actual repo root, not the filesystem root
    clean = _run_heatlint("--layer", "ast", "--format", "sarif")
    assert clean.returncode == 0
    clean_run = json.loads(clean.stdout)["runs"][0]
    assert clean_run["results"] == []
    base = clean_run["originalUriBaseIds"]["SRCROOT"]["uri"]
    assert base.startswith("file://") and base.endswith("/")
    assert base != "file:///"


def test_heatlint_json_schema_v2_and_timings():
    out = _run_heatlint("--layer", "ast", "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["schema_version"] == 2
    assert doc["layers"] == ["ast"]
    assert doc["timings"]["ast"] >= 0
    assert doc["strict_baseline"] is False
    # --format json is the same document
    out2 = _run_heatlint("--layer", "ast", "--format", "json")
    assert json.loads(out2.stdout)["schema_version"] == 2
    # conflicting format flags are a usage error
    bad = _run_heatlint("--json", "--format", "sarif")
    assert bad.returncode == 1


def test_heatlint_strict_baseline_gates_stale(tmp_path):
    # A stale ledger entry is a warning by default but fails the CI
    # gate under --strict-baseline (the make lint mode). Stale-ness is
    # only decided on a full-scope scan — the default repo scope here.
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "HL205", "file": "pkg/gone.py", "symbol": "<module>",
         "justification": "kept: historical"}]}))
    lax_run = _run_heatlint("--layer", "ast", "--baseline", str(bl))
    assert lax_run.returncode == 0
    assert "stale baseline entry" in lax_run.stdout
    strict = _run_heatlint("--layer", "ast", "--baseline", str(bl),
                           "--strict-baseline")
    assert strict.returncode == 2
    # with no stale entries, strict mode stays green
    ok = _run_heatlint("--layer", "ast", "--strict-baseline")
    assert ok.returncode == 0, ok.stdout + ok.stderr


def test_heatlint_path_scoped_run_leaves_baseline_unassessed(tmp_path):
    # A path-scoped AST run never scanned the file a ledger entry
    # excuses, so the entry is unassessed — not stale, and not a
    # strict-mode gate. (Otherwise scanning one clean file under
    # --strict-baseline would tell the user to delete a ledger entry
    # whose violation is still alive elsewhere.)
    viol = tmp_path / "viol.py"
    viol.write_text("import os\n")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "HL205", "file": str(viol), "symbol": "<module>",
         "justification": "kept: fixture"}]}))
    (tmp_path / "clean.py").write_text("x = 1\n")
    scoped = _run_heatlint("--layer", "ast", "--baseline", str(bl),
                           "--strict-baseline",
                           str(tmp_path / "clean.py"))
    assert scoped.returncode == 0, scoped.stdout + scoped.stderr
    assert "stale baseline entry" not in scoped.stdout
    # ...and the entry still matches (suppresses) on a scan that does
    # reach the violation.
    direct = _run_heatlint("--layer", "ast", "--baseline", str(bl),
                           "--strict-baseline", str(viol))
    assert direct.returncode == 0, direct.stdout + direct.stderr


def test_heatlint_layer_selection():
    # Timing summary names exactly the layers run; unknown layers and
    # all+subset combinations are usage errors.
    out = _run_heatlint("--layer", "ast")
    assert "layer timings: ast" in out.stdout
    assert "trace" not in out.stdout
    bad = _run_heatlint("--layer", "nope")
    assert bad.returncode == 1 and "unknown layer" in bad.stderr
    bad2 = _run_heatlint("--layer", "all,ast")
    assert bad2.returncode == 1
    # a rules subset skips layers with no selected rule entirely
    out = _run_heatlint("--rules", "HL205", "--json")
    doc = json.loads(out.stdout)
    assert doc["layers"] == ["ast"]


def test_make_lint_fast_smoke():
    # The pre-commit path: AST-only, jax-free, a few seconds.
    out = subprocess.run(
        ["make", "-C", _ROOT, "lint-fast"], capture_output=True,
        text=True, timeout=300, env={**os.environ})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "layer timings: ast" in out.stdout


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_matrix_dryrun_smoke(tmp_path):
    # The fault x policy sweep must run end to end on CPU and certify
    # its own contract (exit 0 == every bitwise/detection/halt/
    # telemetry check held); the committed chaos_r8_dryrun.json is
    # this exact run. The full matrix (now including the multi-daemon
    # fleet cells) takes minutes of wall — slow tier; `make chaos` and
    # CI's chaos job still run it on every push.
    out_json = tmp_path / "chaos.json"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "chaos_matrix.py"),
         "--dryrun", "--json", str(out_json)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    assert doc["ok"] is True
    outcomes = {r["fault"]: r["outcome"] for r in doc["rows"]}
    assert outcomes["nan_transient"] == "recovered"
    assert outcomes["nan_recurring"] == "halted"
    assert outcomes["unstable"] == "halted"
    assert outcomes["sigterm"] == "interrupted+resumed"
    # the progress-guard cells: a finite spike recovers via the drift
    # envelope (never the nan guard), and the stalled converge run is
    # classified stalled (not nan/transient) within K windows
    assert outcomes["spike_drift"] == "recovered"
    assert outcomes["stalled_converge"] == "halted"
    # the async-save race cells (throttled AsyncCheckpointer): SIGTERM
    # with a save in flight resumes bit-exactly, and a guard trip's
    # rollback drains before generation discovery
    assert outcomes["sigterm_async"] == "interrupted+resumed"
    assert outcomes["nan_async_race"] == "recovered"
    by_fault = {r["fault"]: r for r in doc["rows"]}
    assert by_fault["stalled_converge"]["kind"] == "stalled"
    assert by_fault["stalled_converge"]["telemetry_stall_ok"] is True
    assert by_fault["spike_drift"]["telemetry_drift_ok"] is True
    assert by_fault["nan_async_race"]["telemetry_barrier_ok"] is True
    assert all(r.get("bitwise_match", True) for r in doc["rows"])
    # every solver cell left a parseable event stream, and the NaN
    # cells' guard trips are visible in it within one guard_interval
    # (service cells certify the journal instead)
    assert all(r.get("telemetry_ok", True) for r in doc["rows"])
    assert all(r.get("telemetry_detect_lag_ok", True)
               for r in doc["rows"])
    # the heatd durability cells: true worker death recovered bitwise
    # within one heartbeat timeout, daemon SIGKILL in the accept->
    # dispatch window loses nothing, overload rejects loudly
    assert outcomes["svc_worker_sigkill"] == "recovered"
    assert by_fault["svc_worker_sigkill"]["attempts"] == 2
    assert by_fault["svc_worker_sigkill"]["orphan_detect_ok"] is True
    assert outcomes["svc_daemon_restart"] == "recovered"
    assert outcomes["svc_overload"] == "rejected+served"
    assert by_fault["svc_overload"]["never_dropped_ok"] is True
    # the fleet federation cells: a SIGKILLed host's lease is taken
    # over and its job adopted bitwise within one lease timeout, a
    # raced takeover has exactly one winner, and an exact peer-cache
    # hit is served cross-host with zero dispatches
    assert outcomes["fleet_host_sigkill"] == "recovered"
    assert by_fault["fleet_host_sigkill"]["takeover_bounded_ok"] is True
    assert by_fault["fleet_host_sigkill"]["fleet_check_ok"] is True
    assert outcomes["fleet_lease_race"] == "recovered"
    assert by_fault["fleet_lease_race"]["one_winner_ok"] is True
    assert outcomes["fleet_cache_route"] == "recovered"
    assert by_fault["fleet_cache_route"]["zero_dispatch_ok"] is True
    assert all(r.get("single_terminal_ok", True) for r in doc["rows"])


# ---------------------------------------------------------------------------
# heatd service tooling (ISSUE 8)
# ---------------------------------------------------------------------------

def _mk_queue_root(tmp_path):
    """Hand-built queue root with a controlled journal: jc completed
    first try, jr completed after an orphaning/requeue, jq quarantined,
    jx rejected — timestamps pinned for the percentile math."""
    sys.path.insert(0, _ROOT)
    from parallel_heat_tpu.service.store import JobStore

    root = tmp_path / "q"
    store = JobStore(root)
    j = store.journal
    t = 1000.0
    j.append("daemon_start", t_wall=t, slots=2)
    j.append("accepted", job_id="jc", t_wall=t, hbm_bytes=100)
    j.append("dispatched", job_id="jc", worker="w1", attempt=1,
             t_wall=t + 1.0)
    j.append("completed", job_id="jc", steps_done=60, t_wall=t + 5.0)
    j.append("accepted", job_id="jr", t_wall=t, hbm_bytes=100)
    j.append("dispatched", job_id="jr", worker="w2", attempt=1,
             t_wall=t + 3.0)
    j.append("orphaned", job_id="jr", worker="w2", attempt=1,
             t_wall=t + 4.0)
    j.append("requeued", job_id="jr", reason="orphaned",
             not_before=t + 4.0, t_wall=t + 4.0)
    j.append("dispatched", job_id="jr", worker="w3", attempt=2,
             t_wall=t + 5.0)
    j.append("completed", job_id="jr", steps_done=60, t_wall=t + 9.0)
    j.append("accepted", job_id="jq", t_wall=t, hbm_bytes=100)
    j.append("dispatched", job_id="jq", worker="w4", attempt=1,
             t_wall=t + 2.0)
    j.append("worker_failed", job_id="jq", worker="w4", attempt=1,
             kind="unstable", diagnosis="dt too large",
             t_wall=t + 3.0)
    j.append("quarantined", job_id="jq", kind="unstable",
             reason="fail-fast permanent failure (kind=unstable)",
             t_wall=t + 3.0)
    j.append("rejected", job_id="jx", reason="queue depth 3 at the "
             "admission limit (3)", retry_after_s=2.5, t_wall=t)
    store.write_daemon_status({"pid": 4242, "t_wall": t + 9.0,
                               "state": "serving", "slots": 2,
                               "running_workers": 0, "counts": {},
                               "anomalies": 0})
    store.close()
    return root


def test_metrics_report_fleet_mode(tmp_path):
    root = _mk_queue_root(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    mr = os.path.join(_ROOT, "tools", "metrics_report.py")
    rep = subprocess.run(
        [sys.executable, mr, str(root), "--json"],
        capture_output=True, text=True, timeout=120, env=env)
    # quarantined>0 in the fixture is informational here (no --fail-on
    # threshold): exit 0, the document carries the story
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    f = doc["fleet"]
    assert f["jobs_accepted"] == 3 and f["jobs_rejected"] == 1
    assert f["completed"] == 2 and f["quarantined"] == 1
    assert f["retried"] == 1 and f["orphaned"] == 1
    assert f["attempts_total"] == 4
    # queue waits: 1.0 (jc), 3.0 (jr), 2.0 (jq)
    assert f["queue_wait_s"]["p50"] == _approx(2.0)
    assert f["queue_wait_s"]["max"] == _approx(3.0)
    # job walls: 5.0, 9.0, 3.0
    assert f["job_wall_s"]["max"] == _approx(9.0)
    assert f["quarantined_jobs"][0]["job_id"] == "jq"
    assert doc["anomalies_journal"] == []
    # human rendering names the quarantined job
    txt = subprocess.run([sys.executable, mr, str(root)],
                         capture_output=True, text=True, timeout=120,
                         env=env)
    assert txt.returncode == 0 and "quarantined jq" in txt.stdout
    # the CI gate: --fail-on quarantined>0 -> exit 2
    gate = subprocess.run(
        [sys.executable, mr, str(root), "--fail-on", "quarantined>0"],
        capture_output=True, text=True, timeout=120, env=env)
    assert gate.returncode == 2 and "ANOMALY" in gate.stdout
    # thresholds compose; a satisfied one passes
    ok = subprocess.run(
        [sys.executable, mr, str(root),
         "--fail-on", "quarantined>1,orphaned>1"],
        capture_output=True, text=True, timeout=120, env=env)
    assert ok.returncode == 0
    # unknown counters are loud errors, not silent passes
    bad = subprocess.run(
        [sys.executable, mr, str(root), "--fail-on", "nonsense>0"],
        capture_output=True, text=True, timeout=120, env=env)
    assert bad.returncode == 1 and "not a fleet counter" in bad.stderr
    # a directory that is not a queue root is unusable input
    notq = subprocess.run(
        [sys.executable, mr, str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env)
    assert notq.returncode == 1


def _approx(x):
    return pytest.approx(x, abs=1e-6)


def test_metrics_report_fleet_anomaly_gate(tmp_path):
    # a journal whose replay reports a durability anomaly (double
    # terminal) must exit 2 even with no --fail-on
    sys.path.insert(0, _ROOT)
    from parallel_heat_tpu.service.store import JobStore

    root = tmp_path / "q"
    store = JobStore(root)
    store.journal.append("accepted", job_id="a")
    store.journal.append("completed", job_id="a")
    store.journal.append("cancelled", job_id="a")  # double terminal
    store.close()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(root)],
        capture_output=True, text=True, timeout=120, env=env)
    assert rep.returncode == 2
    assert "durability" in rep.stdout


def test_monitor_daemon_view_once(tmp_path):
    root = _mk_queue_root(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    mon = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "monitor.py"),
         "--once", "--daemon", str(root)],
        capture_output=True, text=True, timeout=60, env=env)
    assert mon.returncode == 0, mon.stderr[-2000:]
    line = mon.stdout.strip()
    assert "heatd pid 4242" in line or "serving" in line
    assert "completed=2" in line
    assert "quarantined=1" in line
    assert "rejected=1" in line
    # after a drain, the view says so (and live mode would exit)
    sys.path.insert(0, _ROOT)
    from parallel_heat_tpu.service.store import JobStore

    store = JobStore(root, create=False)
    store.journal.append("daemon_exit", outcome="drained")
    store.close()
    mon2 = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "monitor.py"),
         "--once", "--daemon", str(root)],
        capture_output=True, text=True, timeout=60, env=env)
    assert "daemon exited (drained)" in mon2.stdout


def test_monitor_daemon_queue_depth_and_oldest_age(tmp_path):
    # The live view of the queue-wait SLO (ISSUE 12 satellite): depth
    # counts every non-terminal job, and the oldest-ACCEPTED age names
    # how long the head of the queue has been waiting for a slot.
    root = _mk_queue_root(tmp_path)
    sys.path.insert(0, _ROOT)
    from parallel_heat_tpu.service.store import JobStore

    store = JobStore(root, create=False)
    store.journal.append("accepted", job_id="jqueued", hbm_bytes=100,
                         t_wall=2000.0)
    store.journal.append("accepted", job_id="jrun", hbm_bytes=100,
                         t_wall=2100.0)
    store.journal.append("dispatched", job_id="jrun", worker="w9",
                         attempt=1, t_wall=2101.0)
    store.close()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    mon = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "monitor.py"),
         "--once", "--daemon", str(root)],
        capture_output=True, text=True, timeout=60, env=env)
    assert mon.returncode == 0, mon.stderr[-2000:]
    line = mon.stdout.strip()
    # depth = jqueued (queued) + jrun (running) = 2; the oldest QUEUED
    # age anchors at jqueued's accepted stamp (2000.0 — far in this
    # test's past, so the age is large)
    assert "depth 2" in line
    assert "oldest queued" in line
    import re

    age = float(re.search(r"oldest queued ([0-9.]+)s", line).group(1))
    assert age > 1000  # anchored at the pinned t_wall, not at now


def test_metrics_report_fleet_dotted_path_threshold(tmp_path):
    # The shared threshold grammar (tools/slo_gate.py reuses it):
    # dotted paths reach nested fleet numbers like queue_wait_s.p99.
    root = _mk_queue_root(tmp_path)
    mr = os.path.join(_ROOT, "tools", "metrics_report.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # jc waited 1s, jr 3s, jq 2s -> p99 = 3 > 2.5 trips
    bad = subprocess.run(
        [sys.executable, mr, str(root),
         "--fail-on", "queue_wait_s.p99>2.5"],
        capture_output=True, text=True, timeout=120, env=env)
    assert bad.returncode == 2, bad.stderr[-2000:]
    assert "queue_wait_s.p99" in bad.stdout
    ok = subprocess.run(
        [sys.executable, mr, str(root),
         "--fail-on", "queue_wait_s.p99>10"],
        capture_output=True, text=True, timeout=120, env=env)
    assert ok.returncode == 0, ok.stderr[-2000:]
    # floors work on fleet counters too (completed<N as a liveness
    # floor), and malformed tokens stay loud errors
    floor = subprocess.run(
        [sys.executable, mr, str(root), "--fail-on", "completed<3"],
        capture_output=True, text=True, timeout=120, env=env)
    assert floor.returncode == 2 and "completed = 2 < 3" in floor.stdout
    badtok = subprocess.run(
        [sys.executable, mr, str(root), "--fail-on", "completed>x"],
        capture_output=True, text=True, timeout=120, env=env)
    assert badtok.returncode == 1 and "bad threshold token" \
        in badtok.stderr
