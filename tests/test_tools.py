"""Smoke tests for the tools/ scripts (they must not rot)."""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.parametrize("extra", [[], ["--halo-depth", "2"]])
def test_scaling_study_smoke(extra):
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "scaling_study.py"),
         "--cpu-devices", "4", "--sizes", "64", "--meshes", "1x1,2x2",
         "--steps", "20", "--repeats", "1", "--backend", "jnp"] + extra,
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    assert {r["mesh"] for r in rows} == {"1x1", "2x2"}
    assert all(r["wall_s"] > 0 for r in rows)
    assert "| mesh 2x2" in out.stdout  # the reference-style table


def test_bench_importable_and_baseline_set():
    sys.path.insert(0, _ROOT)
    try:
        import bench

        assert bench.BASELINE_MCELLS_PER_S > 0
        assert callable(bench.main)
    finally:
        sys.path.remove(_ROOT)


def test_ab_uni_single_smoke(tmp_path):
    # The windowed-vs-uniform A/B harness must run end to end (tiny
    # grid, interpret-mode kernels) and emit its JSON artifact with
    # rates for both kernel-E schedules.
    out_json = tmp_path / "ab_uni.json"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "ab_uni_single.py"),
         "--size", "64", "--json", str(out_json)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    row = doc["rows"]["64x64 float32"]
    assert "E (windowed)" in row["gcells_steps_per_s"]
    assert "E-uni (uniform gather)" in row["gcells_steps_per_s"]
    assert "pick_single_2d" in out.stdout


def test_headline_variance_row_specs():
    # The variance protocol's row table must stay in sync with
    # bench.py's stdout contract fields.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hv", os.path.join(_ROOT, "tools", "headline_variance.py"))
    hv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hv)
    assert set(hv._ROWS) == {"headline", "conv256"}
    assert hv._ROWS["conv256"]["field"] == "wall_s"
    assert hv._ROWS["headline"]["field"] == "value"


def test_make_heat_smoke():
    # The reference-style Make entry point must stay runnable.
    run = lambda *a: subprocess.run(
        ["make", "-C", _ROOT, *a], capture_output=True, text=True,
        timeout=300, env={**os.environ})
    out = run("heat", "SIZE=32", "STEPS=10", "BACKEND=jnp")
    assert out.returncode == 0, out.stderr[-2000:]
    assert os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    assert os.path.exists(os.path.join(_ROOT, "initial_im.dat"))
    out = run("clean")
    assert out.returncode == 0
    assert not os.path.exists(os.path.join(_ROOT, "final_im.dat"))
    # clean also drops the native build; restore it so later suites
    # don't pay a rebuild
    assert run("native").returncode == 0


def test_metrics_report_round_trip(tmp_path):
    # CLI --metrics -> JSONL -> tools/metrics_report.py --json: the
    # full telemetry pipeline, as `make telemetry-smoke` drives it.
    m = tmp_path / "m.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    run = subprocess.run(
        [sys.executable, "-m", "parallel_heat_tpu", "--nx", "32",
         "--ny", "32", "--steps", "60", "--backend", "jnp",
         "--supervise", "--checkpoint", str(tmp_path / "ck"),
         "--checkpoint-every", "20", "--guard-interval", "10",
         "--metrics", str(m), "--quiet"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert run.returncode == 0, run.stderr[-2000:]
    rep = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--json"],
        capture_output=True, text=True, timeout=300, env=env)
    assert rep.returncode == 0, rep.stderr[-2000:]
    doc = json.loads(rep.stdout)
    assert doc["header"]["config"]["nx"] == 32
    assert doc["chunks"]["count"] == 6
    assert doc["chunks"]["steps_total"] == 60
    assert doc["chunks"]["steps_per_s"]["p50"] > 0
    assert doc["checkpoints"]["saves"] == 4
    assert 0 < doc["checkpoints"]["overhead_share"] <= 1
    assert doc["outcome"] == "complete" and doc["anomalies"] == []
    # the human-readable rendering works on the same stream
    txt = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(m)],
        capture_output=True, text=True, timeout=300, env=env)
    assert txt.returncode == 0 and "outcome: complete" in txt.stdout
    # anomaly thresholds drive the exit code (CI contract): a
    # checkpoint-share ceiling this tiny run must exceed -> exit 2
    bad = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"),
         str(m), "--max-ckpt-share", "0.0000001"],
        capture_output=True, text=True, timeout=300, env=env)
    assert bad.returncode == 2 and "ANOMALY" in bad.stdout
    # unusable input is distinct from an anomaly -> exit 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    none = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "metrics_report.py"), str(empty)],
        capture_output=True, text=True, timeout=300, env=env)
    assert none.returncode == 1


@pytest.mark.chaos
def test_chaos_matrix_dryrun_smoke(tmp_path):
    # The fault x policy sweep must run end to end on CPU and certify
    # its own contract (exit 0 == every bitwise/detection/halt/
    # telemetry check held); the committed chaos_r8_dryrun.json is
    # this exact run.
    out_json = tmp_path / "chaos.json"
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "chaos_matrix.py"),
         "--dryrun", "--json", str(out_json)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_ROOT,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out_json.read_text())
    assert doc["ok"] is True
    outcomes = {r["fault"]: r["outcome"] for r in doc["rows"]}
    assert outcomes["nan_transient"] == "recovered"
    assert outcomes["nan_recurring"] == "halted"
    assert outcomes["unstable"] == "halted"
    assert outcomes["sigterm"] == "interrupted+resumed"
    assert all(r.get("bitwise_match", True) for r in doc["rows"])
    # every cell left a parseable event stream, and the NaN cells'
    # guard trips are visible in it within one guard_interval
    assert all(r["telemetry_ok"] for r in doc["rows"])
    assert all(r.get("telemetry_detect_lag_ok", True)
               for r in doc["rows"])
