"""The heatd service layer: durable store, admission, scheduler.

Everything here is fast and deterministic — the daemon is driven
step-by-step on injected clocks with fake worker handles (the journal
and the scheduling decisions are what's under test; real process death
and real subprocess workers live in ``tests/test_chaos.py`` and the
``tools/chaos_matrix.py`` service cells). The contract pinned
(SEMANTICS.md "Job durability"): an ACCEPTED job is never silently
lost — it reaches exactly one terminal state or sits in the journal
with its resume state; rejections are loud, first-class, and carry a
retry-after hint.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from parallel_heat_tpu.service.admission import (
    admission_verdict,
    estimate_job_hbm_bytes,
)
from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
from parallel_heat_tpu.service.store import (
    JobSpec,
    JobStore,
    read_journal_file,
    reduce_journal,
)
from parallel_heat_tpu.service import client

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Test doubles
# ---------------------------------------------------------------------------

class FakeClock:
    """Deterministic daemon time source."""

    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeHandle:
    """Popen-shaped worker handle whose exit the test scripts."""

    def __init__(self, rc=None):
        self.rc = rc
        self.pid = os.getpid()
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


class ScriptedLauncher:
    """Collects dispatches; each returns a :class:`FakeHandle` the
    test later finishes by setting ``rc`` + writing a result record."""

    def __init__(self):
        self.dispatches = []

    def __call__(self, job_id, worker_id, attempt, deadline_t):
        h = FakeHandle()
        self.dispatches.append(
            {"job_id": job_id, "worker_id": worker_id,
             "attempt": attempt, "deadline_t": deadline_t,
             "handle": h})
        return h

    def last(self, job_id):
        for d in reversed(self.dispatches):
            if d["job_id"] == job_id:
                return d
        raise KeyError(job_id)


def _daemon(root, clock=None, launcher=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("requeue_backoff_base_s", 0.0)
    cfg = HeatdConfig(root=str(root),
                      clock=clock or FakeClock(),
                      sleep_fn=lambda s: None,
                      launcher=launcher or ScriptedLauncher(), **kw)
    return Heatd(cfg)


def _spec(job_id, nx=16, steps=60, **kw):
    return JobSpec(job_id=job_id,
                   config={"nx": nx, "ny": nx, "steps": steps,
                           "backend": "jnp"}, **kw)


def _finish(store, d, outcome, rc=0, **fields):
    """Land a worker outcome: rename-commit the result record, then
    let the next reconcile observe the exit."""
    doc = {"outcome": outcome, "worker": d["worker_id"],
           "attempt": d["attempt"], "job_id": d["job_id"]}
    doc.update(fields)
    store.write_result(d["job_id"], d["attempt"], doc)
    d["handle"].rc = rc


def _events(store, job_id=None, event=None):
    evs, _, _ = store.read_journal()
    return [e for e in evs
            if (job_id is None or e.get("job_id") == job_id)
            and (event is None or e.get("event") == event)]


# ---------------------------------------------------------------------------
# Journal + reducer (the durability substrate)
# ---------------------------------------------------------------------------

def test_journal_append_replay_roundtrip(tmp_path):
    store = JobStore(tmp_path / "q")
    store.journal.append("accepted", job_id="a", hbm_bytes=7)
    store.journal.append("dispatched", job_id="a", worker="w1",
                         attempt=1)
    store.journal.append("completed", job_id="a", steps_done=60)
    jobs, anomalies = store.replay()
    assert anomalies == []
    v = jobs["a"]
    assert v.state == "completed" and v.terminal
    assert v.attempts == 1 and v.worker == "w1"
    assert v.hbm_bytes == 7 and v.steps_done == 60
    store.close()


def test_journal_torn_tail_skipped(tmp_path):
    path = tmp_path / "journal.jsonl"
    lines = [json.dumps({"event": "accepted", "job_id": "a"}),
             json.dumps({"event": "completed", "job_id": "a"})]
    path.write_text("\n".join(lines) + "\n"
                    + '{"event": "dispatched", "job_id"')  # torn append
    events, bad, torn = read_journal_file(path)
    assert torn is True and bad == 0
    assert [e["event"] for e in events] == ["accepted", "completed"]
    jobs, anomalies = reduce_journal(events)
    assert jobs["a"].state == "completed" and anomalies == []


def test_journal_interior_garbage_counted_not_fatal(tmp_path):
    path = tmp_path / "journal.jsonl"
    path.write_text(json.dumps({"event": "accepted", "job_id": "a"})
                    + "\nnot json at all\n"
                    + json.dumps({"event": "completed", "job_id": "a"})
                    + "\n")
    events, bad, torn = read_journal_file(path)
    assert bad == 1 and torn is False and len(events) == 2


def test_reducer_terminal_state_is_absorbing():
    events = [{"event": "accepted", "job_id": "a", "t_wall": 1.0},
              {"event": "dispatched", "job_id": "a", "worker": "w1",
               "attempt": 1, "t_wall": 2.0},
              {"event": "completed", "job_id": "a", "t_wall": 3.0},
              {"event": "completed", "job_id": "a", "t_wall": 4.0}]
    jobs, anomalies = reduce_journal(events)
    assert jobs["a"].state == "completed"
    assert jobs["a"].terminal_t == 3.0  # the first terminal wins
    assert any("double terminal" in a for a in anomalies)


def test_reducer_dispatch_after_terminal_is_anomalous():
    events = [{"event": "accepted", "job_id": "a", "t_wall": 1.0},
              {"event": "cancelled", "job_id": "a", "t_wall": 2.0},
              {"event": "dispatched", "job_id": "a", "worker": "w9",
               "attempt": 1, "t_wall": 3.0}]
    jobs, anomalies = reduce_journal(events)
    assert jobs["a"].state == "cancelled"
    assert anomalies


def test_reducer_missing_accepted_is_anomalous():
    jobs, anomalies = reduce_journal(
        [{"event": "completed", "job_id": "ghost", "t_wall": 1.0}])
    assert "ghost" in jobs
    assert any("missing" in a for a in anomalies)


def test_reducer_ignores_foreign_and_daemon_lines():
    events = [{"event": "daemon_start", "pid": 1, "t_wall": 0.0},
              {"event": "accepted", "job_id": "a", "t_wall": 1.0},
              {"not_an_event": True},
              {"event": "totally_unknown", "job_id": "a"}]
    jobs, anomalies = reduce_journal(events)
    assert jobs["a"].state == "queued" and anomalies == []


def test_jobspec_roundtrip_ignores_unknown_fields():
    spec = _spec("j1", deadline_s=5.0, max_retries=7)
    doc = json.loads(spec.to_json())
    doc["from_the_future"] = {"x": 1}
    back = JobSpec.from_json(json.dumps(doc))
    assert back == spec


def test_atomic_record_temp_invisible_to_discovery(tmp_path):
    store = JobStore(tmp_path / "q")
    # A writer died mid-write: its dotted temp must not be discovered.
    spool = os.path.join(str(tmp_path / "q"), "spool")
    with open(os.path.join(spool, ".tmp-999-torn.json"), "w") as f:
        f.write('{"job_id": "torn"')
    store.spool_submit(_spec("real"))
    assert store.iter_spool() == ["real"]


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

def test_estimate_hbm_scales_with_grid_and_dtype():
    b2d = estimate_job_hbm_bytes({"nx": 100, "ny": 50,
                                  "dtype": "float32"})
    assert b2d == 100 * 50 * 4 * 3
    b3d = estimate_job_hbm_bytes({"nx": 10, "ny": 10, "nz": 10,
                                  "dtype": "bfloat16"})
    assert b3d == 1000 * 2 * 3


def test_admission_depth_gate():
    ok, reason, retry, _ = admission_verdict(
        {"nx": 16, "ny": 16}, active_jobs=4, active_hbm_bytes=0,
        max_queue_depth=4, hbm_budget_bytes=None,
        retry_after_base_s=2.0, slots=2)
    assert not ok and "queue depth" in reason and retry > 0


def test_admission_hbm_gate():
    est = estimate_job_hbm_bytes({"nx": 256, "ny": 256})
    ok, reason, retry, got_est = admission_verdict(
        {"nx": 256, "ny": 256}, active_jobs=1,
        active_hbm_bytes=100, max_queue_depth=16,
        hbm_budget_bytes=est + 50, retry_after_base_s=1.0, slots=1)
    assert not ok and "HBM" in reason and got_est == est


def test_admission_draining_rejects():
    ok, reason, retry, _ = admission_verdict(
        {"nx": 16, "ny": 16}, 0, 0, 16, None, 1.0, 2, draining=True)
    assert not ok and "draining" in reason and retry > 0


def test_admission_retry_after_scales_with_backlog():
    def retry(active):
        return admission_verdict({"nx": 16, "ny": 16}, active, 0,
                                 1, None, 2.0, slots=2)[2]
    assert retry(8) > retry(2) > 0


def test_admission_accepts_within_budget():
    ok, reason, retry, est = admission_verdict(
        {"nx": 16, "ny": 16}, 0, 0, 16, 2**30, 1.0, 2)
    assert ok and reason is None and retry == 0.0 and est > 0


# ---------------------------------------------------------------------------
# Daemon scheduling (fake clock + scripted workers)
# ---------------------------------------------------------------------------

def test_accept_dispatch_complete_lifecycle(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    jobs, _ = d.store.replay()
    assert jobs["j1"].state == "running" and jobs["j1"].attempts == 1
    assert d.store.iter_spool() == []  # spool drained post-accept
    assert d.store.load_spec("j1").job_id == "j1"  # durable record
    _finish(d.store, launcher.last("j1"), "completed", steps_done=60)
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["j1"].state == "completed" and anomalies == []
    # exactly one of each lifecycle line
    for ev in ("accepted", "dispatched", "completed"):
        assert len(_events(d.store, "j1", ev)) == 1, ev
    d.store.close()


def test_admission_handshake_idempotent_after_crash(tmp_path):
    # Crash window: journal says accepted but the spool entry
    # survived (daemon died before the unlink). The restarted daemon
    # must finish the handshake without a second accepted line.
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    d.store.spool_submit(_spec("j1"))  # resurrect the spool copy
    d2 = _daemon(tmp_path / "q", launcher=launcher)
    d2.step()
    assert len(_events(d2.store, "j1", "accepted")) == 1
    assert d2.store.iter_spool() == []
    _, anomalies = d2.store.replay()
    assert anomalies == []
    d.store.close()
    d2.store.close()


def test_reject_past_queue_depth_with_retry_after(tmp_path):
    d = _daemon(tmp_path / "q", max_queue_depth=1)
    d.store.spool_submit(_spec("j1"))
    d.step()
    d.store.spool_submit(_spec("j2"))
    d.step()
    jobs, _ = d.store.replay()
    assert jobs["j2"].state == "rejected"
    assert jobs["j2"].retry_after_s > 0
    assert "queue depth" in jobs["j2"].reason
    # a rejected job never acquires execution state
    assert _events(d.store, "j2", "dispatched") == []
    d.store.close()


def test_failfast_kind_quarantines_immediately(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher, quarantine_after=3)
    d.store.spool_submit(_spec("j1"))
    d.step()
    _finish(d.store, launcher.last("j1"), "permanent_failure", rc=4,
            kind="unstable", diagnosis="eps too large")
    d.step()
    jobs, _ = d.store.replay()
    assert jobs["j1"].state == "quarantined"
    assert jobs["j1"].kind == "unstable"
    assert jobs["j1"].diagnosis == "eps too large"
    assert jobs["j1"].distinct_failed_workers == 1  # no retry burn
    d.store.close()


def test_transient_requeues_with_bounded_backoff_then_quarantines(
        tmp_path):
    clock = FakeClock(0.0)
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", clock=clock, launcher=launcher,
                quarantine_after=3, requeue_backoff_base_s=0.5,
                requeue_backoff_max_s=0.75)
    d.store.spool_submit(_spec("j1"))
    d.step()
    for n in (1, 2):
        _finish(d.store, launcher.last("j1"), "permanent_failure",
                rc=4, kind="exhausted")
        d.step()  # classify + requeue with backoff
        req = _events(d.store, "j1", "requeued")[-1]
        # bounded exponential: min(max, base * 2**(n-1))
        assert req["backoff_s"] == min(0.75, 0.5 * 2 ** (n - 1))
        jobs, _ = d.store.replay()
        assert jobs["j1"].state == "queued"
        d.step()  # backoff not yet elapsed: must NOT redispatch
        jobs, _ = d.store.replay()
        assert jobs["j1"].state == "queued"
        clock.advance(1.0)
        d.step()  # due now
        jobs, _ = d.store.replay()
        assert jobs["j1"].state == "running"
        assert jobs["j1"].attempts == n + 1
    _finish(d.store, launcher.last("j1"), "permanent_failure", rc=4,
            kind="exhausted")
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["j1"].state == "quarantined"  # 3 distinct workers
    assert jobs["j1"].distinct_failed_workers == 3
    assert anomalies == []
    q = _events(d.store, "j1", "quarantined")[0]
    assert "distinct" in q["reason"]
    d.store.close()


def test_worker_death_without_record_is_orphaned_and_requeued(
        tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    # SIGKILL: the process exits with no outcome record at all.
    launcher.last("j1")["handle"].rc = -signal.SIGKILL
    d.step()
    orphan = _events(d.store, "j1", "orphaned")
    assert len(orphan) == 1 and "without an outcome" in orphan[0][
        "reason"]
    jobs, _ = d.store.replay()
    assert jobs["j1"].state == "running"  # already requeued+redispatched
    assert jobs["j1"].attempts == 2
    assert _events(d.store, "j1", "requeued")
    d.store.close()


def test_adopted_job_with_result_record_is_journaled_once(tmp_path):
    # Daemon restarted after dispatch; the worker finished meanwhile.
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    dsp = launcher.last("j1")
    d.store.write_result("j1", 1, {"outcome": "completed",
                                   "worker": dsp["worker_id"],
                                   "attempt": 1, "steps_done": 60})
    d.store.close()
    d2 = _daemon(tmp_path / "q")  # fresh: no Popen handles
    d2.step()
    jobs, anomalies = d2.store.replay()
    assert jobs["j1"].state == "completed" and anomalies == []
    assert len(_events(d2.store, "j1", "completed")) == 1
    d2.store.close()


def test_adopted_job_stale_heartbeat_orphans_within_timeout(tmp_path):
    clock = FakeClock(1000.0)
    launcher = ScriptedLauncher()
    timeout = 3.0
    d = _daemon(tmp_path / "q", clock=clock, launcher=launcher,
                worker_heartbeat_s=0.5, heartbeat_timeout_s=timeout)
    d.store.spool_submit(_spec("j1"))
    d.step()
    wid = launcher.last("j1")["worker_id"]
    d.store.close()
    d2 = _daemon(tmp_path / "q", clock=clock, worker_heartbeat_s=0.5,
                 heartbeat_timeout_s=timeout)
    # Live pid + fresh beat: NOT orphaned.
    d2.store.write_worker_hb(wid, {"pid": os.getpid(),
                                   "t_wall": clock.t})
    d2.step()
    assert _events(d2.store, "j1", "orphaned") == []
    # Beat goes stale past the timeout: orphaned on the next pass,
    # even though the recorded pid (this test) is alive — a wedged
    # worker that stopped beating is as dead as a SIGKILLed one.
    clock.advance(timeout + 0.1)
    d2.step()
    assert len(_events(d2.store, "j1", "orphaned")) == 1
    d2.store.close()


def test_cancel_queued_job(tmp_path):
    clock = FakeClock()
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", clock=clock, launcher=launcher,
                slots=1)
    d.store.spool_submit(_spec("j1"))
    d.store.spool_submit(_spec("j2"))  # queued behind j1 (1 slot)
    d.step()
    assert client.cancel(str(tmp_path / "q"), "j2") is True
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["j2"].state == "cancelled" and anomalies == []
    assert d.store.cancel_requests() == []  # marker cleared
    # unknown/terminal jobs: nothing to do
    assert client.cancel(str(tmp_path / "q"), "j2") is False
    assert client.cancel(str(tmp_path / "q"), "nope") is False
    d.store.close()


def test_cancel_running_job_interrupts_then_journals_cancelled(
        tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    dsp = launcher.last("j1")
    client.cancel(str(tmp_path / "q"), "j1")
    d.step()
    assert dsp["handle"].terminated  # flag-only SIGTERM path
    # The worker flushes its checkpoint and records "preempted"; with
    # the cancel marker set, that maps to the cancelled terminal.
    _finish(d.store, dsp, "preempted", rc=3, reason="SIGTERM",
            steps_done=20)
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["j1"].state == "cancelled" and anomalies == []
    assert jobs["j1"].steps_done == 20
    d.store.close()


def test_sigterm_escalates_to_sigkill_past_grace(tmp_path):
    clock = FakeClock()
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", clock=clock, launcher=launcher,
                kill_grace_s=5.0)
    d.store.spool_submit(_spec("j1"))
    d.step()
    dsp = launcher.last("j1")
    client.cancel(str(tmp_path / "q"), "j1")
    d.step()
    assert dsp["handle"].terminated and not dsp["handle"].killed
    clock.advance(6.0)  # wedged past the grace
    d.step()
    assert dsp["handle"].killed
    d.store.close()


def test_deadline_expired_while_queued(tmp_path):
    # deadline_s=0: expired the moment it is accepted. Real clock —
    # deadline_t derives from the journal's wall stamps, so a fake
    # daemon clock would never reach it.
    import time

    d = _daemon(tmp_path / "q", clock=time.time, slots=1,
                launcher=ScriptedLauncher())
    d.store.spool_submit(_spec("j1"))
    d.store.spool_submit(_spec("j2", deadline_s=0.0))
    d.step()
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["j2"].state == "deadline_expired" and anomalies == []
    d.store.close()


def test_deadline_passed_to_worker_launcher(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1", deadline_s=3600.0))
    d.step()
    dsp = launcher.last("j1")
    jobs, _ = d.store.replay()
    assert dsp["deadline_t"] == pytest.approx(jobs["j1"].deadline_t)
    assert dsp["deadline_t"] is not None
    d.store.close()


def test_dispatch_respects_slots_and_fifo_order(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher, slots=2)
    for i in range(4):
        d.store.spool_submit(_spec(f"j{i}"))
        d.step()
    assert [x["job_id"] for x in launcher.dispatches] == ["j0", "j1"]
    _finish(d.store, launcher.last("j0"), "completed")
    d.step()
    assert [x["job_id"] for x in launcher.dispatches][-1] == "j2"
    d.store.close()


def test_drain_keeps_queued_jobs_and_rejects_spool(tmp_path):
    from parallel_heat_tpu.supervisor import EXIT_PREEMPTED

    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher, slots=1,
                drain_grace_s=0.0)
    d.store.spool_submit(_spec("j1"))
    d.store.spool_submit(_spec("j2"))  # queued behind j1
    d.step()
    dsp = launcher.last("j1")
    d.store.spool_submit(_spec("late"))  # arrives as the drain starts

    # The in-flight worker flushes on SIGTERM like a real one would.
    real_terminate = dsp["handle"].terminate

    def terminate_and_flush():
        real_terminate()
        _finish(d.store, dsp, "preempted", rc=3, reason="SIGTERM",
                steps_done=30)
    dsp["handle"].terminate = terminate_and_flush

    rc = d.drain(reason="test")
    assert rc == EXIT_PREEMPTED
    jobs, anomalies = d.store.replay()
    assert anomalies == []
    assert jobs["late"].state == "rejected"
    assert "draining" in jobs["late"].reason
    assert jobs["j2"].state == "queued"  # durable, restart dispatches
    assert jobs["j1"].state == "queued"  # journaled resume state
    assert jobs["j1"].steps_done == 30
    evs = [e["event"] for e in _events(d.store)]
    assert "daemon_drain" in evs and "daemon_exit" in evs
    # the restarted daemon picks both up
    launcher2 = ScriptedLauncher()
    d2 = _daemon(tmp_path / "q", launcher=launcher2, slots=2)
    d2.step()
    assert {x["job_id"] for x in launcher2.dispatches} == {"j1", "j2"}
    assert launcher2.last("j1")["attempt"] == 2
    d2.store.close()


def test_heatd_config_validation_errors(tmp_path):
    with pytest.raises(ValueError, match="slots"):
        HeatdConfig(root=str(tmp_path), slots=0).validate()
    with pytest.raises(ValueError, match="heartbeat_timeout_s"):
        HeatdConfig(root=str(tmp_path), worker_heartbeat_s=2.0,
                    heartbeat_timeout_s=1.0).validate()
    with pytest.raises(ValueError, match="quarantine_after"):
        HeatdConfig(root=str(tmp_path), quarantine_after=0).validate()


def test_status_heartbeat_published(tmp_path):
    d = _daemon(tmp_path / "q")
    d.store.spool_submit(_spec("j1"))
    summary = d.step()
    assert summary["state"] == "serving"
    doc = d.store.read_daemon_status()
    assert doc["pid"] == os.getpid()
    assert doc["counts"] == {"running": 1}
    d.store.close()


# ---------------------------------------------------------------------------
# Client + end-to-end inline execution
# ---------------------------------------------------------------------------

def test_client_submit_times_out_actionably(tmp_path):
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(s):
        t["now"] += s

    with pytest.raises(TimeoutError, match="heatd serve"):
        client.submit(str(tmp_path / "q"), {"nx": 16, "ny": 16},
                      accept_timeout_s=5.0, clock=clock, sleep_fn=sleep)


def test_client_submit_sees_rejection(tmp_path):
    d = _daemon(tmp_path / "q", max_queue_depth=1)
    d.store.spool_submit(_spec("occupant"))
    d.step()
    t = {"now": 0.0}

    def sleep(s):
        t["now"] += s
        d.step()

    verdict = client.submit(str(tmp_path / "q"), {"nx": 16, "ny": 16},
                            job_id="j2", accept_timeout_s=30.0,
                            clock=lambda: t["now"], sleep_fn=sleep)
    assert verdict == {"job_id": "j2", "accepted": False,
                       "reason": verdict["reason"],
                       "retry_after_s": verdict["retry_after_s"],
                       "trace_id": verdict["trace_id"]}
    assert verdict["retry_after_s"] > 0
    # the trace is born at submit even for a rejected submission (the
    # rejection is part of the causal story)
    assert verdict["trace_id"].startswith("t")
    d.store.close()


def test_make_job_id_unique():
    ids = {client.make_job_id() for _ in range(100)}
    assert len(ids) == 100


def test_inline_job_executes_and_matches_unsupervised_solve(tmp_path):
    # One REAL solve through the whole service path (inline worker —
    # subprocess workers are the chaos suite's job): accepted,
    # dispatched, supervised with per-job checkpoint dir + telemetry
    # sink, completed; final checkpoint bitwise the plain solve().
    from parallel_heat_tpu import HeatConfig, solve
    from parallel_heat_tpu.service.harness import inline_launcher
    from parallel_heat_tpu.utils.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
    )

    root = str(tmp_path / "q")
    d = _daemon(root, launcher=inline_launcher(root))
    d.store.spool_submit(_spec("j1", checkpoint_every=20,
                               guard_interval=10))
    for _ in range(4):
        d.step()
        jobs, _ = d.store.replay()
        if jobs["j1"].terminal:
            break
    jobs, anomalies = d.store.replay()
    assert jobs["j1"].state == "completed" and anomalies == []
    assert jobs["j1"].steps_done == 60

    cfg = HeatConfig(nx=16, ny=16, steps=60, backend="jnp")
    src = latest_checkpoint(d.store.checkpoint_stem("j1"))
    grid, step, _ = load_checkpoint(src, cfg)
    assert step == 60
    np.testing.assert_array_equal(np.asarray(grid),
                                  solve(cfg).to_numpy())
    # the per-job telemetry sink recorded the run
    assert os.path.getsize(d.store.telemetry_path("j1")) > 0
    # result record round trip
    rec = d.store.read_result("j1", 1)
    assert rec["outcome"] == "completed" and rec["steps_done"] == 60
    d.store.close()


# ---------------------------------------------------------------------------
# heatd CLI surface
# ---------------------------------------------------------------------------

def test_heatd_cli_status_and_cancel_errors(tmp_path, capsys):
    from parallel_heat_tpu.service.cli import main as heatd_main

    root = str(tmp_path / "q")
    d = _daemon(root, max_queue_depth=1)
    d.store.spool_submit(_spec("j1"))
    d.step()
    d.store.close()
    assert heatd_main(["status", "--queue", root, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["jobs"]["j1"]["state"] == "running"
    assert doc["anomalies"] == []
    assert heatd_main(["cancel", "--queue", root, "nope"]) == 2
    assert heatd_main(["cancel", "--queue", root, "j1"]) == 0


def test_solver_cli_forwards_service_commands(tmp_path, capsys):
    # `python -m parallel_heat_tpu status --queue ...` is the same
    # surface as the heatd console script.
    from parallel_heat_tpu.cli import main as solver_main

    root = str(tmp_path / "q")
    d = _daemon(root)
    d.step()
    d.store.close()
    assert solver_main(["status", "--queue", root, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["daemon"]["state"] == "serving"


def test_heatd_cli_drain_without_daemon(tmp_path, capsys):
    from parallel_heat_tpu.service.cli import main as heatd_main

    os.makedirs(tmp_path / "q", exist_ok=True)
    assert heatd_main(["drain", "--queue", str(tmp_path / "q")]) == 2


def test_worker_default_checkpoint_cadence_f32chunk_aligned():
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.supervisor import default_checkpoint_every

    plain = HeatConfig(nx=16, ny=16, steps=100, backend="jnp")
    assert default_checkpoint_every(plain) == 10
    chunked = HeatConfig(nx=16, ny=16, steps=100, backend="jnp",
                         dtype="bfloat16", accumulate="f32chunk")
    # bf16 sublane multiple is 16: 10 rounds up to 16
    assert default_checkpoint_every(chunked) == 16


def test_heatq_inspector_check_gate(tmp_path):
    # tools/heatq.py: --check exits 2 exactly when the journal replay
    # reports a durability anomaly.
    root = tmp_path / "q"
    store = JobStore(root)
    store.journal.append("accepted", job_id="a")
    store.journal.append("dispatched", job_id="a", worker="w1",
                         attempt=1)
    store.journal.append("completed", job_id="a", steps_done=60)
    store.close()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    heatq = os.path.join(_ROOT, "tools", "heatq.py")
    out = subprocess.run(
        [sys.executable, heatq, str(root), "--json", "--check"],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["counts"] == {"completed": 1}
    assert doc["jobs"][0]["attempts"] == 1
    assert doc["anomalies"] == []
    # now break the invariant: a second terminal state
    store2 = JobStore(root)
    store2.journal.append("completed", job_id="a")
    store2.close()
    bad = subprocess.run(
        [sys.executable, heatq, str(root), "--check"],
        capture_output=True, text=True, timeout=120, env=env)
    assert bad.returncode == 2
    assert "ANOMALY" in bad.stdout


# ---------------------------------------------------------------------------
# Review-fix regressions
# ---------------------------------------------------------------------------

def test_reducer_incremental_fold_equivalence(tmp_path):
    # reduce(prefix) then reduce(suffix, state) == reduce(prefix +
    # suffix): the fold law the daemon's O(new events) incremental
    # replay rests on.
    events = [
        {"event": "accepted", "job_id": "a", "t_wall": 1.0,
         "hbm_bytes": 5},
        {"event": "dispatched", "job_id": "a", "worker": "w1",
         "attempt": 1, "t_wall": 2.0},
        {"event": "orphaned", "job_id": "a", "worker": "w1",
         "attempt": 1, "t_wall": 3.0},
        {"event": "requeued", "job_id": "a", "reason": "orphaned",
         "not_before": 3.5, "t_wall": 3.5},
        {"event": "dispatched", "job_id": "a", "worker": "w2",
         "attempt": 2, "t_wall": 4.0},
        {"event": "completed", "job_id": "a", "steps_done": 60,
         "t_wall": 9.0},
        {"event": "rejected", "job_id": "b", "reason": "depth",
         "retry_after_s": 1.0, "t_wall": 2.0},
        {"event": "completed", "job_id": "a", "t_wall": 10.0},  # anomaly
    ]
    for cut in range(len(events) + 1):
        full = reduce_journal(events)
        state = reduce_journal(events[:cut])
        inc = reduce_journal(events[cut:], state=state)
        assert inc == full, cut


def test_daemon_incremental_replay_matches_store_replay(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.store.spool_submit(_spec("j2"))
    d.step()
    _finish(d.store, launcher.last("j1"), "permanent_failure", rc=4,
            kind="exhausted")
    d.step()
    _finish(d.store, launcher.last("j2"), "completed", steps_done=60)
    d.step()
    assert d._replay() == d.store.replay()
    d.store.close()


def test_adopted_worker_gets_dispatch_grace_before_orphaning(tmp_path):
    import time

    # Restarted daemon adopts a running job whose worker has not
    # written its FIRST heartbeat yet (still importing its runtime):
    # within one heartbeat timeout of the dispatch stamp it must NOT
    # be orphaned — orphaning would spawn a second live worker.
    root = tmp_path / "q"
    store = JobStore(root)
    store.commit_job_record(_spec("j1"))
    store.journal.append("accepted", job_id="j1")
    store.journal.append("dispatched", job_id="j1", worker="w1",
                         attempt=1)
    store.close()
    d = _daemon(root, clock=time.time, launcher=ScriptedLauncher(),
                worker_heartbeat_s=0.1, heartbeat_timeout_s=0.3)
    d.step()
    assert _events(d.store, "j1", "orphaned") == []  # grace
    time.sleep(0.35)  # past the timeout, still no first beat: corpse
    d.step()
    assert len(_events(d.store, "j1", "orphaned")) == 1
    d.store.close()


def test_bad_spec_records_failfast_quarantine(tmp_path):
    # An accepted spec the worker cannot materialize must produce a
    # rename-committed bad_spec record (fail-fast quarantine with THE
    # diagnosis), not a recordless death churning through
    # orphan/requeue to a mislabeled verdict.
    from parallel_heat_tpu.service.harness import inline_launcher
    from parallel_heat_tpu.supervisor import EXIT_PERMANENT_FAILURE

    root = str(tmp_path / "q")
    d = _daemon(root, launcher=inline_launcher(root))
    d.store.spool_submit(JobSpec(
        job_id="jbad", config={"nx": 2, "ny": 2, "steps": 60}))  # < 3
    d.step()
    d.step()
    jobs, anomalies = d.store.replay()
    assert jobs["jbad"].state == "quarantined" and anomalies == []
    assert jobs["jbad"].kind == "bad_spec"
    assert jobs["jbad"].attempts == 1  # fail-fast: no retry burn
    rec = d.store.read_result("jbad", 1)
    assert rec["outcome"] == "permanent_failure"
    assert "cannot materialize" in rec["diagnosis"]
    d.store.close()


def test_cancel_reaches_adopted_worker_via_heartbeat_pid(tmp_path):
    import time

    # Daemon restarted while a job runs: no Popen handle, but the
    # worker heartbeat names its pid — cancellation must still
    # interrupt it (SIGTERM through the same flag-only contract).
    root = tmp_path / "q"
    victim = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(60)"])
    try:
        store = JobStore(root)
        store.commit_job_record(_spec("j1"))
        store.journal.append("accepted", job_id="j1")
        store.journal.append("dispatched", job_id="j1", worker="w1",
                             attempt=1)
        store.write_worker_hb("w1", {"pid": victim.pid,
                                     "t_wall": time.time()})
        store.close()
        d = _daemon(root, clock=time.time,
                    launcher=ScriptedLauncher())
        assert client.cancel(str(root), "j1") is True
        d.step()
        assert victim.wait(timeout=30) == -signal.SIGTERM
        # the dead worker's job then resolves through reconcile: the
        # cancel marker maps the eventual orphaning to `cancelled`
        time.sleep(0.1)
        for _ in range(60):
            d.step()
            jobs, _ = d.store.replay()
            if jobs["j1"].terminal:
                break
            time.sleep(0.1)
        jobs, anomalies = d.store.replay()
        assert jobs["j1"].state == "cancelled" and anomalies == []
        d.store.close()
    finally:
        if victim.poll() is None:
            victim.kill()
            victim.wait()


def test_client_rejects_reused_job_id(tmp_path):
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher)
    d.store.spool_submit(_spec("j1"))
    d.step()
    _finish(d.store, launcher.last("j1"), "completed")
    d.step()
    with pytest.raises(ValueError, match="single-use"):
        client.submit(str(tmp_path / "q"), {"nx": 16, "ny": 16},
                      job_id="j1", accept_timeout_s=1.0)
    # CLI spelling: exit 2, loud
    from parallel_heat_tpu.service.cli import main as heatd_main

    assert heatd_main(["submit", "--queue", str(tmp_path / "q"),
                       "--job-id", "j1", "--nx", "16", "--ny",
                       "16"]) == 2
    d.store.close()


def test_stem_lock_concurrent_stale_reclaim_single_winner(tmp_path):
    # TOCTOU regression: many threads racing to reclaim the same
    # STALE lock must produce exactly one holder (the flock sidecar
    # serializes the judge-unlink-retake sequence; without it a loser
    # could unlink the winner's fresh lock and co-hold the stem).
    import json as _json
    import threading

    from parallel_heat_tpu.utils.checkpoint import (
        StemLockError,
        _stem_lock_path,
        acquire_stem_lock,
    )

    stem = str(tmp_path / "ck")
    os.makedirs(tmp_path, exist_ok=True)
    with open(_stem_lock_path(stem), "w") as f:
        _json.dump({"pid": 2 ** 22 + 3, "t_wall": 0.0}, f)  # dead pid
    wins, errs = [], []
    barrier = threading.Barrier(8)

    def racer():
        barrier.wait()
        try:
            wins.append(acquire_stem_lock(stem))
        except StemLockError:
            errs.append(1)

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1 and len(errs) == 7
    assert os.path.exists(_stem_lock_path(stem))
    wins[0]()
    assert not os.path.exists(_stem_lock_path(stem))


def test_rejection_then_acceptance_same_pass_keeps_fold_consistent(
        tmp_path):
    # A rejection and a later acceptance land in ONE _admit pass: the
    # acceptance's offset bump must not skip the rejection's journal
    # bytes — the cached fold has to keep matching a full replay (a
    # skipped rejection would undercount forever AND let a re-used id
    # through the idempotent-handshake dedupe).
    launcher = ScriptedLauncher()
    d = _daemon(tmp_path / "q", launcher=launcher, max_queue_depth=1,
                slots=1)
    d.store.spool_submit(_spec("occupant"))
    d.step()
    # spool iterates sorted: "a-rejected" (depth gate: occupant is
    # active) then "b-also-rejected"; on the next pass after occupant
    # completes, "c-accepted" goes through — interleaving verdicts.
    d.store.spool_submit(_spec("a-rejected"))
    d.store.spool_submit(_spec("b-also-rejected"))
    d.step()
    _finish(d.store, launcher.last("occupant"), "completed")
    d.store.spool_submit(_spec("c-accepted"))
    d.step()
    assert d._replay() == d.store.replay()
    jobs, _ = d.store.replay()
    assert jobs["a-rejected"].state == "rejected"
    assert jobs["b-also-rejected"].state == "rejected"
    assert jobs["c-accepted"].state == "running"
    # the daemon's status heartbeat counts the rejections (folded)
    doc = d.store.read_daemon_status()
    assert doc["counts"].get("rejected") == 2
    # and a re-used rejected id is still deduped, not re-answered
    d.store.spool_submit(_spec("a-rejected"))
    d.step()
    assert len(_events(d.store, "a-rejected", "rejected")) == 1
    d.store.close()
