import pytest

from parallel_heat_tpu import HeatConfig


def test_defaults_mirror_reference_macros():
    c = HeatConfig()
    assert (c.nx, c.ny) == (20, 20)
    assert (c.cx, c.cy) == (0.1, 0.1)
    assert c.check_interval == 20
    assert c.eps == 1e-3
    c.validate()


def test_shape_and_block_shape():
    c = HeatConfig(nx=64, ny=32, mesh_shape=(2, 4)).validate()
    assert c.shape == (64, 32)
    assert c.block_shape() == (32, 8)
    assert c.mesh_or_unit() == (2, 4)
    assert HeatConfig().mesh_or_unit() == (1, 1)


def test_3d_shape():
    c = HeatConfig(nx=8, ny=8, nz=8).validate()
    assert c.ndim == 3
    assert c.shape == (8, 8, 8)
    assert c.coefficients == (0.1, 0.1, 0.1)


@pytest.mark.parametrize(
    "kw",
    [
        dict(nx=2),
        dict(steps=-1),
        dict(converge=True, check_interval=0),
        dict(converge=True, eps=0.0),
        dict(dtype="int8"),
        dict(backend="cuda"),
        dict(mesh_shape=(3,)),          # rank mismatch
        dict(nx=20, mesh_shape=(3, 1)),  # 20 % 3 != 0
        dict(mesh_shape=(0, 1)),
        dict(halo_overlap="async"),      # not a schedule name
    ],
)
def test_validation_rejects(kw):
    with pytest.raises(ValueError):
        HeatConfig(**kw).validate()


def test_halo_overlap_values_validate():
    # Every schedule spelling validates, on sharded and unsharded
    # configs alike (inert unsharded — like `overlap`).
    for v in (None, "auto", "phase", "overlap", "pipeline"):
        HeatConfig(halo_overlap=v).validate()
        HeatConfig(nx=32, ny=32, mesh_shape=(2, 2), halo_depth=4,
                   halo_overlap=v).validate()
    # and the field is classified SEMANTIC (HL101's partition)
    from parallel_heat_tpu.config import SEMANTIC_FIELDS

    assert "halo_overlap" in SEMANTIC_FIELDS


def test_json_roundtrip():
    c = HeatConfig(nx=128, ny=64, steps=500, converge=True,
                   mesh_shape=(2, 2), dtype="bfloat16")
    c2 = HeatConfig.from_json(c.to_json())
    assert c2 == c


def test_stability_margin():
    assert HeatConfig(cx=0.1, cy=0.1).stability_margin() == pytest.approx(0.3)
    assert HeatConfig(cx=0.3, cy=0.3).stability_margin() < 0
    assert HeatConfig(nx=8, ny=8, nz=8, cx=0.1, cy=0.1,
                      cz=0.1).stability_margin() == pytest.approx(0.2)


def test_unstable_coefficients_actually_diverge():
    # the property the margin predicts: an unstable run blows up
    import warnings

    import numpy as np

    from parallel_heat_tpu import solve

    cfg = HeatConfig(nx=16, ny=16, steps=500, cx=0.3, cy=0.3,
                     backend="jnp")
    assert cfg.stability_margin() < 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # intentional
        out = solve(cfg).to_numpy()
    assert not np.all(np.isfinite(out)) or np.max(np.abs(out)) > 1e18


def test_unstable_coefficients_warn_on_validate():
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        HeatConfig(cx=0.3, cy=0.3).validate()
    assert any("stability bound" in str(x.message) for x in w)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        HeatConfig(cx=0.1, cy=0.1).validate()
    assert not w


def test_f64_deep_halo_any_depth_validates():
    # f64 routes to the jnp path for every backend choice (Mosaic has
    # no 64-bit types), and the jnp rounds support any depth — so the
    # pallas depth==sublane restriction must not fire for f64
    # (regression: explicit pallas + f64 + halo_depth=4 raised even
    # though the program that actually runs supports it).
    import jax

    was = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        cfg = HeatConfig(nx=64, ny=64, dtype="float64", backend="pallas",
                         mesh_shape=(2, 2), halo_depth=4)
        cfg.validate()  # must not raise
    finally:
        jax.config.update("jax_enable_x64", was)
