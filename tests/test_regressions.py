"""Regression tests for review findings."""

import jax.numpy as jnp
import numpy as np

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.models import HeatPlate2D
from parallel_heat_tpu.solver import make_initial_grid


def test_degenerate_blocks_extent_one():
    # mesh (8,1) on nx=8 -> bx=1 blocks; overlap path must degrade to
    # the padded formulation instead of mis-shaping the carry.
    want = solve(HeatConfig(nx=8, ny=16, steps=3, backend="jnp")).to_numpy()
    for mesh in [(8, 1), (1, 8), (8, 1)]:
        got = solve(
            HeatConfig(nx=8, ny=16, steps=3, backend="jnp",
                       mesh_shape=mesh, overlap=True)
        ).to_numpy()
        np.testing.assert_array_equal(got, want)


def test_caller_initial_not_invalidated_by_donation():
    cfg = HeatConfig(nx=12, ny=12, steps=5, backend="jnp")
    u0 = make_initial_grid(cfg)
    r1 = solve(cfg, initial=u0)
    r2 = solve(cfg, initial=u0)  # would raise on a donated buffer
    np.testing.assert_array_equal(r1.to_numpy(), r2.to_numpy())
    # and u0 itself is still readable
    assert float(jnp.max(u0)) > 0


def test_device_init_bitwise_matches_f64_oracle_at_scale():
    # Per-axis factoring makes device init bit-identical to the
    # float64-then-cast oracle for axes <= 8192.
    m = HeatPlate2D(1024, 768)
    got = np.asarray(m.init_grid(jnp.float32))
    want = m.init_grid_np(np.float32)
    np.testing.assert_array_equal(got, want)


def test_vmem_kernel_boundary_pinned_even_when_diverging():
    # Kernel A pins Dirichlet columns via coefficient vectors; when a
    # diverging run drives neighbors to inf, 0*inf=NaN must not leak
    # into the output boundary (snapshot/restore guards it).
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # intentional instability
        cfg = HeatConfig(nx=32, ny=32, steps=400, cx=0.3, cy=0.3,
                         backend="pallas")
        u0 = make_initial_grid(cfg)
        res = solve(cfg, initial=u0)
    out = res.to_numpy()
    u0 = np.asarray(u0)
    assert not np.all(np.isfinite(out))  # it did diverge
    np.testing.assert_array_equal(out[0, :], u0[0, :])
    np.testing.assert_array_equal(out[-1, :], u0[-1, :])
    np.testing.assert_array_equal(out[:, 0], u0[:, 0])
    np.testing.assert_array_equal(out[:, -1], u0[:, -1])


def test_temporal_kernel_boundary_pinned_even_when_diverging():
    # Kernel E pins the boundary with multiplicative coefficient
    # vectors; on a diverging run 0*inf = NaN inside the kernel must
    # not leak into the output boundary — ``fn`` re-pins it from the
    # untouched input (the four .at[].set() guards). This locks that
    # guard in: without it, the stable-run suite stays green because
    # the re-pin is a bitwise no-op there.
    from parallel_heat_tpu.ops.pallas_stencil import _build_temporal_strip

    fn = _build_temporal_strip((256, 256), "float32", 0.9, 0.9, 8)
    assert fn is not None
    u0 = HeatPlate2D(256, 256).init_grid(jnp.float32)
    u = u0
    for _ in range(20):
        u, _ = fn(u)
    out, ini = np.asarray(u), np.asarray(u0)
    assert not np.all(np.isfinite(out))  # it did diverge
    np.testing.assert_array_equal(out[0, :], ini[0, :])
    np.testing.assert_array_equal(out[-1, :], ini[-1, :])
    np.testing.assert_array_equal(out[:, 0], ini[:, 0])
    np.testing.assert_array_equal(out[:, -1], ini[:, -1])


def test_streaming_pickers_decline_non_lane_aligned_widths(monkeypatch):
    # Mosaic rejects lane-dim slice extents that are not multiples of
    # 128 (real-TPU compile error at 5000^2); when compiling for
    # hardware the streaming pickers must decline so the solver falls
    # back to the jnp path. (The interpreter has no such constraint —
    # the CPU suite deliberately uses small unaligned widths.)
    import parallel_heat_tpu.ops.pallas_stencil as ps

    monkeypatch.setattr(ps, "_interpret", lambda: False)  # hardware mode
    assert ps._pick_strip_rows(5000, 5000, "float32", sharded=False) is None
    assert ps._pick_temporal_strip(5000, 5000, "float32") is None
    # aligned widths still tile
    assert ps._pick_temporal_strip(5120, 5120, "float32") is not None
    monkeypatch.undo()
    assert ps._pick_temporal_strip(5000, 5000, "float32") is not None


def test_xslab_picker_declines_unaligned_y(monkeypatch):
    # Full-plane DMAs slice the sublane dim at extent Y; Mosaic needs
    # it tile-aligned (Y=300 was a real-TPU compile error).
    import parallel_heat_tpu.ops.pallas_stencil as ps

    monkeypatch.setattr(ps, "_interpret", lambda: False)
    assert ps._pick_xslab_3d((300, 300, 384), "float32") is None
    assert ps._pick_xslab_3d((320, 320, 384), "float32") is not None
