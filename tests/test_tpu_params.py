"""Per-device-generation parameter table (ops/tpu_params.py).

The pickers must re-budget when the device generation changes: a faked
16 MiB-VMEM v3 must shrink or decline picks a 128 MiB v5e admits, and
the kernel F scorer must respond to the bandwidth/VPU ratios. On CPU
(this suite) the fallback row is v5e, pinning picker decisions to the
hardware-validated ones.
"""

import pytest

from parallel_heat_tpu.ops import pallas_stencil as ps
from parallel_heat_tpu.ops import tpu_params as tp


@pytest.fixture
def restore():
    yield
    tp.set_override(None)


def test_classify_device_kind():
    assert tp.classify_device_kind("TPU v5 lite") == "v5e"
    assert tp.classify_device_kind("TPU v5e") == "v5e"
    assert tp.classify_device_kind("TPU v5p") == "v5p"
    assert tp.classify_device_kind("TPU v5") == "v5p"
    assert tp.classify_device_kind("TPU v6 lite") == "v6e"
    assert tp.classify_device_kind("TPU v6e") == "v6e"
    assert tp.classify_device_kind("TPU v4") == "v4"
    assert tp.classify_device_kind("TPU v4 lite") == "v4"
    assert tp.classify_device_kind("TPU v3") == "v3"
    assert tp.classify_device_kind("TPU v2") == "v2"
    assert tp.classify_device_kind("TPU weird future") == "v5e"


def test_default_params_off_tpu_is_v5e():
    assert tp.params().kind == "v5e"
    assert tp.params().vmem_bytes == 128 * 1024 * 1024
    # derived budgets match the round-1 measured-safe literals
    assert tp.params().resident_budget_bytes == 80 * 1024 * 1024
    assert tp.params().stream_budget_bytes == 100 * 1024 * 1024


def test_env_override_selects_row(monkeypatch):
    monkeypatch.setenv("PHT_TPU_KIND", "TPU v4")
    assert tp.params().kind == "v4"


def test_v3_budget_shrinks_picks(restore):
    # v5e admits a 4096-wide f32 strip pick; a 16 MiB v3 must not.
    t_v5e = ps._pick_strip_rows(4096, 4096, "float32", sharded=False)
    assert t_v5e is not None
    tp.set_override(tp._TABLE["v3"])
    t_v3 = ps._pick_strip_rows(4096, 4096, "float32", sharded=False)
    assert t_v3 is None or t_v3 < t_v5e
    # resident kernel A: a grid that fits v5e VMEM does not fit v3
    assert not ps.fits_vmem((1024, 1024), "float32")
    tp.set_override(None)
    assert ps.fits_vmem((1024, 1024), "float32")


def test_xslab_scorer_responds_to_ratios(restore):
    # On a generation with much higher bandwidth per VPU-cell (v5p),
    # the scorer still returns a valid (sx, K) and the modeled regime
    # shift never crashes the picker.
    pick_v5e = ps._pick_xslab_3d((512, 512, 512), "float32")
    assert pick_v5e is not None
    tp.set_override(tp._TABLE["v5p"])
    pick_v5p = ps._pick_xslab_3d((512, 512, 512), "float32")
    assert pick_v5p is not None
    sx, k = pick_v5p
    assert 512 % sx == 0 and 1 <= k <= 8
    # Faster HBM relative to VPU favors (weakly) fewer temporal steps.
    assert k <= pick_v5e[1]


def test_sane_picks_across_all_rows(restore):
    # Every table row yields either a decline or a self-consistent pick
    # for the flagship geometries (no crashes, no budget violations).
    for kind, row in tp._TABLE.items():
        tp.set_override(row)
        t = ps._pick_strip_rows(16384, 16384, "float32", sharded=False)
        if t is not None:
            assert 16384 % t == 0 and t % 8 == 0
        pick = ps._pick_xslab_3d((512, 512, 512), "float32")
        if pick is not None:
            assert 512 % pick[0] == 0
