"""Test configuration: run everything on 8 virtual CPU devices.

The distributed paths (``shard_map`` + ``ppermute``) then run on CPU
exactly as they would over an 8-chip ICI mesh (SURVEY.md §4). A pytest
plugin imports jax before this conftest loads, so env vars are too late;
``jax.config.update`` still works because the backend itself is only
initialized on first use.
"""

import os

# Older jax (< 0.5) has no jax_num_cpu_devices config; the XLA flag is
# the portable spelling and must be set before the backend initializes.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")  # the shell pins a TPU platform
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.5 jax: the XLA flag above already did it
    pass

assert len(jax.devices()) == 8, (
    "tests require 8 virtual CPU devices; got " + str(jax.devices())
)
