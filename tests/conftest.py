"""Test configuration: run everything on 8 virtual CPU devices.

The distributed paths (``shard_map`` + ``ppermute``) then run on CPU
exactly as they would over an 8-chip ICI mesh (SURVEY.md §4). A pytest
plugin imports jax before this conftest loads, so env vars are too late;
``jax.config.update`` still works because the backend itself is only
initialized on first use.
"""

import jax

jax.config.update("jax_platforms", "cpu")  # the shell pins a TPU platform
jax.config.update("jax_num_cpu_devices", 8)

assert len(jax.devices()) == 8, (
    "tests require 8 virtual CPU devices; got " + str(jax.devices())
)
