"""In-run numerics diagnostics: the fused grid-stats reduction
(`solver.grid_stats`, `HeatConfig.diag_interval`), the supervisor's
progress guard (stall / drift classification), and the multi-process
telemetry sharding — all under the guard's observation-only contract
(SEMANTICS.md)."""

import json
import math
import warnings

import numpy as np
import pytest

from parallel_heat_tpu import (
    HeatConfig,
    PermanentFailure,
    SupervisorPolicy,
    Telemetry,
    grid_stats,
    run_supervised,
    solve,
    solve_stream,
)
from parallel_heat_tpu.utils.faults import FaultPlan

_BASE = dict(nx=16, ny=16, backend="jnp")


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _stalling_initial(n=16):
    """A hot-boundary start state whose converge run deterministically
    stalls: the f32 iteration toward the nonzero steady state ends in a
    rounding limit cycle with a flat residual (2^-15) above eps=1e-6 —
    the exact 'eps below the reachable floor' pathology the progress
    guard exists to classify."""
    u0 = np.zeros((n, n), np.float32)
    u0[0, :] = 1000.0
    return u0


_STALL_CFG = HeatConfig(steps=4000, converge=True, check_interval=10,
                        eps=1e-6, **_BASE)


# -- grid_stats ------------------------------------------------------------

def test_grid_stats_matches_numpy():
    rng = np.random.default_rng(7)
    u = rng.normal(size=(24, 24)).astype(np.float32)
    prev = rng.normal(size=(24, 24)).astype(np.float32)
    s = grid_stats(u, prev=prev)
    assert s["min"] == pytest.approx(u.min())
    assert s["max"] == pytest.approx(u.max())
    assert s["heat"] == pytest.approx(float(u.sum()), rel=1e-5)
    d = u - prev
    assert s["update_l2"] == pytest.approx(
        float(np.sqrt((d * d).sum())), rel=1e-5)
    assert s["update_linf"] == pytest.approx(float(np.abs(d).max()))
    solo = grid_stats(u)
    assert solo["update_l2"] is None and solo["update_linf"] is None
    assert solo["min"] == s["min"] and solo["heat"] == s["heat"]


def test_grid_stats_bf16_accumulates_f32():
    # 256 cells of 1.0 in bf16: a bf16-accumulated sum would lose
    # low-order adds (bf16 resolution at 256 is 2); the f32 accumulator
    # must report the exact count.
    import jax.numpy as jnp

    u = jnp.ones((16, 16), jnp.bfloat16)
    assert grid_stats(u)["heat"] == 256.0


# -- stream sampling -------------------------------------------------------

def test_stream_diag_sampling_schedule(tmp_path):
    p = tmp_path / "t.jsonl"
    cfg = HeatConfig(steps=50, diag_interval=20, **_BASE)
    rs, grids = [], {}
    with Telemetry(p) as tel:
        for r in solve_stream(cfg, chunk_steps=10, telemetry=tel):
            # consume each grid before advancing (the next chunk
            # donates it)
            grids[r.steps_run] = r.to_numpy()
            rs.append(r)
    # First boundary at-or-after 20, 40, plus the final chunk.
    sampled = [r.steps_run for r in rs if r.diagnostics is not None]
    assert sampled == [20, 40, 50]
    diags = [e for e in _events(p) if e["event"] == "diagnostics"]
    assert [d["step"] for d in diags] == [20, 40, 50]
    assert [d["steps_since"] for d in diags] == [20, 20, 10]
    # Stats agree with the yielded grids (the boundary grid IS the
    # sampled grid), and the update norms are the diff between samples.
    g20, g40 = grids[20], grids[40]
    d = diags[1]
    assert d["min"] == pytest.approx(g40.min())
    assert d["max"] == pytest.approx(g40.max())
    assert d["heat"] == pytest.approx(float(g40.sum()), rel=1e-5)
    diff = g40 - g20
    assert d["update_linf"] == pytest.approx(float(np.abs(diff).max()))
    assert d["update_l2"] == pytest.approx(
        float(np.sqrt((diff * diff).sum())), rel=1e-5)
    # chunks without a sample carry None
    assert all(r.diagnostics is None for r in rs
               if r.steps_run not in sampled)


def test_solve_samples_final_grid():
    cfg = HeatConfig(steps=30, diag_interval=10, **_BASE)
    r = solve(cfg)
    assert r.diagnostics is not None
    assert r.diagnostics["step"] == 30
    g = r.to_numpy()
    assert r.diagnostics["max"] == pytest.approx(g.max())
    # the update baseline is the initial condition
    assert r.diagnostics["update_linf"] > 0
    assert solve(cfg.replace(diag_interval=None)).diagnostics is None


def test_diag_is_observation_only():
    # The acceptance contract: diag-enabled runs share compiled
    # programs (no new _build_runner misses) and produce bitwise grids.
    from parallel_heat_tpu import solver

    cfg = HeatConfig(steps=30, **_BASE)
    solver._build_runner.cache_clear()
    plain = [r.to_numpy() for r in solve_stream(cfg, chunk_steps=10)]
    misses = solver._build_runner.cache_info().misses
    diag = [r.to_numpy()
            for r in solve_stream(cfg.replace(diag_interval=10),
                                  chunk_steps=10)]
    assert solver._build_runner.cache_info().misses == misses
    for a, b in zip(plain, diag):
        np.testing.assert_array_equal(a, b)


def test_explain_reports_diagnostics():
    from parallel_heat_tpu.solver import explain

    out = explain(HeatConfig(steps=10, diag_interval=25, **_BASE))
    assert "every 25 steps" in out["diagnostics"]
    assert "diagnostics" not in explain(HeatConfig(steps=10, **_BASE))


def test_diag_interval_validation():
    with pytest.raises(ValueError, match="diag_interval"):
        HeatConfig(diag_interval=0, **_BASE).validate()


# -- progress guard: stall -------------------------------------------------

def test_supervisor_classifies_stall(tmp_path):
    p = tmp_path / "t.jsonl"
    policy = SupervisorPolicy(checkpoint_every=500, guard_interval=250,
                              stall_windows=4, backoff_base_s=0.0)
    with Telemetry(p) as tel:
        with pytest.raises(PermanentFailure) as ei:
            run_supervised(_STALL_CFG, tmp_path / "ck", policy=policy,
                           initial=_stalling_initial(), telemetry=tel)
    # Classified STALLED — not nan, not transient, no retry burned.
    assert ei.value.kind == "stalled"
    assert "residual stalled" in ei.value.diagnosis
    assert "4 consecutive windows" in ei.value.diagnosis
    ev = _events(p)
    trip = next(e for e in ev if e["event"] == "progress_trip")
    assert trip["kind"] == "stalled" and trip["windows"] == 4
    lo, hi = trip["window"]
    assert hi - lo == 4 * 250  # the stall window spans exactly K chunks
    assert trip["residual"] == pytest.approx(2.0 ** -15)
    assert not any(e["event"] in ("guard_trip", "retry") for e in ev)
    end = ev[-1]
    assert end["event"] == "run_end"
    assert end["outcome"] == "permanent_failure"
    assert end["kind"] == "stalled"


def test_stall_classifier_stays_quiet_on_healthy_decay(tmp_path):
    # A healthily converging run keeps setting new minima: the
    # classifier must never fire, and the run must converge.
    cfg = HeatConfig(steps=10_000, converge=True, check_interval=20,
                     eps=1e-3, **_BASE)
    policy = SupervisorPolicy(checkpoint_every=200, guard_interval=100,
                              stall_windows=3, backoff_base_s=0.0)
    sres = run_supervised(cfg, tmp_path / "ck", policy=policy)
    assert sres.result.converged
    assert sres.progress_trips == 0


# -- progress guard: drift -------------------------------------------------

def test_drift_trip_recovers_from_transient_spike(tmp_path):
    p = tmp_path / "t.jsonl"
    cfg = HeatConfig(steps=60, **_BASE)
    policy = SupervisorPolicy(checkpoint_every=20, guard_interval=10,
                              drift_tolerance=0.01, backoff_base_s=0.0)
    with Telemetry(p) as tel:
        sres = run_supervised(cfg, tmp_path / "ck", policy=policy,
                              faults=FaultPlan(spike_at_step=35),
                              telemetry=tel)
    # One-shot finite corruption: the NaN guard is blind to it, the
    # drift envelope catches it, rollback replays clean to completion.
    assert sres.retries == 1 and sres.progress_trips == 1
    assert sres.guard_trips == 0
    assert sres.steps_done == 60
    clean = solve(cfg)
    np.testing.assert_array_equal(sres.result.to_numpy(),
                                  clean.to_numpy())
    ev = _events(p)
    trip = next(e for e in ev if e["event"] == "progress_trip")
    assert trip["kind"] == "drift" and "envelope" in trip["detail"]
    assert not any(e["event"] == "guard_trip" for e in ev)


def test_drift_heat_rate_catches_in_envelope_corruption(tmp_path):
    # Region-scale corruption that stays INSIDE the extrema envelope
    # (a buggy exchange zeroing a block): invisible to both the NaN
    # guard and the maximum-principle check, caught by the
    # boundary-flux rate bound on total heat content.
    p = tmp_path / "t.jsonl"
    cfg = HeatConfig(steps=60, **_BASE)
    policy = SupervisorPolicy(checkpoint_every=20, guard_interval=10,
                              drift_tolerance=0.01, backoff_base_s=0.0)
    # zero the central 13x13 block: all values remain in [min0, max0],
    # but ~206k of heat vanishes in one 10-step window against a
    # boundary-flux limit of ~184k
    faults = FaultPlan(spike_at_step=35, spike_value=0.0,
                       spike_region=13)
    with Telemetry(p) as tel:
        sres = run_supervised(cfg, tmp_path / "ck", policy=policy,
                              faults=faults, telemetry=tel)
    assert sres.progress_trips == 1 and sres.guard_trips == 0
    assert sres.steps_done == 60
    trip = next(e for e in _events(p)
                if e["event"] == "progress_trip")
    assert trip["kind"] == "drift"
    assert "boundary-flux bound" in trip["detail"]


def test_faultplan_rejects_nan_and_spike_together():
    with pytest.raises(ValueError, match="not both"):
        FaultPlan(nan_at_step=10, spike_at_step=30)


def test_drift_recurring_halts_with_drift_kind(tmp_path):
    cfg = HeatConfig(steps=60, **_BASE)
    policy = SupervisorPolicy(checkpoint_every=20, guard_interval=10,
                              drift_tolerance=0.01, max_retries=2,
                              backoff_base_s=0.0)
    with pytest.raises(PermanentFailure) as ei:
        run_supervised(cfg, tmp_path / "ck", policy=policy,
                       faults=FaultPlan(spike_at_step=35,
                                        recurring=True))
    assert ei.value.kind == "drift"
    assert "heat-content drift" in ei.value.diagnosis


def test_drift_guard_quiet_on_clean_run(tmp_path):
    sres = run_supervised(
        HeatConfig(steps=60, **_BASE), tmp_path / "ck",
        policy=SupervisorPolicy(checkpoint_every=20, guard_interval=10,
                                drift_tolerance=0.01,
                                backoff_base_s=0.0))
    assert sres.progress_trips == 0 and sres.steps_done == 60


def test_policy_validates_progress_knobs():
    with pytest.raises(ValueError, match="stall_windows"):
        SupervisorPolicy(stall_windows=0).validate()
    with pytest.raises(ValueError, match="drift_tolerance"):
        SupervisorPolicy(drift_tolerance=-0.1).validate()


def test_cli_rejects_inert_progress_flags(tmp_path, capsys):
    from parallel_heat_tpu.cli import main

    # progress-guard flags without --supervise: loud error
    assert main(["--nx", "16", "--ny", "16", "--steps", "10",
                 "--stall-windows", "3"]) == 2
    assert "--supervise" in capsys.readouterr().err
    # --stall-windows on a fixed-step run would be silently inert
    # (no residual to classify): loud error instead
    assert main(["--nx", "16", "--ny", "16", "--steps", "10",
                 "--supervise", "--checkpoint", str(tmp_path / "ck"),
                 "--stall-windows", "3"]) == 2
    assert "--converge" in capsys.readouterr().err
    # --monitor-hint with nothing to monitor: loud error
    assert main(["--nx", "16", "--ny", "16", "--steps", "10",
                 "--monitor-hint"]) == 2
    assert "--metrics" in capsys.readouterr().err


def test_resume_command_carries_progress_flags(tmp_path):
    from parallel_heat_tpu.supervisor import _resume_command
    from parallel_heat_tpu.utils.checkpoint import checkpoint_stem

    cfg = HeatConfig(steps=100, diag_interval=25, **_BASE)
    policy = SupervisorPolicy(stall_windows=3, drift_tolerance=0.05)
    cmd = _resume_command(cfg, checkpoint_stem(tmp_path / "ck"), 100,
                          policy.validate())
    assert "--diag-interval 25" in cmd
    assert "--stall-windows 3" in cmd
    assert "--drift-tolerance 0.05" in cmd


# -- multi-process telemetry sharding --------------------------------------

def test_telemetry_shards_per_process(tmp_path):
    base = tmp_path / "m.jsonl"
    hb = tmp_path / "hb.json"
    with Telemetry(base, heartbeat=hb, process_index=1,
                   process_count=3) as tel:
        tel.emit("chunk", step=5)
    shard = tmp_path / "m.p1.jsonl"
    assert shard.exists() and not base.exists()
    assert (tmp_path / "hb.p1.json").exists() and not hb.exists()
    ev = _events(shard)[0]
    assert ev["process_index"] == 1 and ev["process_count"] == 3


def test_telemetry_single_process_path_unchanged(tmp_path):
    p = tmp_path / "m.jsonl"
    with Telemetry(p) as tel:
        tel.emit("chunk", step=5)
    assert p.exists()
    ev = _events(p)[0]
    assert ev["process_index"] == 0 and ev["process_count"] == 1


def test_heartbeat_payload_self_sufficient(tmp_path):
    # last_step / last_event / residual ride the heartbeat so probes
    # (and monitor --once) need not parse the JSONL at all.
    hb = tmp_path / "hb.json"
    cfg = HeatConfig(nx=12, ny=12, steps=200, converge=True,
                     check_interval=20, eps=1e-12, backend="jnp")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with Telemetry(tmp_path / "m.jsonl", heartbeat=hb) as tel:
            for _ in solve_stream(cfg, chunk_steps=100, telemetry=tel):
                pass
    doc = json.load(open(hb))
    assert doc["last_step"] == 200 and doc["step"] == 200
    # the chunk's prof-plane attribution segment lands right after it
    assert doc["last_event"] == "profile"
    assert doc["residual"] is not None
    assert math.isfinite(doc["residual"])
