"""Smoke tests of the driver-facing entry points on the CPU mesh."""

import sys

import jax
import numpy as np


def _repo_on_path():
    root = __file__.rsplit("/tests/", 1)[0]
    if root not in sys.path:
        sys.path.insert(0, root)


def test_graft_entry_compiles_and_runs():
    _repo_on_path()
    import __graft_entry__ as g

    fn, args = g.entry()
    new, res = jax.jit(fn)(*args)
    assert new.shape == args[0].shape
    assert float(res) > 0  # initial condition is not a fixed point


def test_dryrun_multichip_8():
    _repo_on_path()
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_bench_helper_on_tiny_config():
    _repo_on_path()
    import bench
    from parallel_heat_tpu import HeatConfig

    elapsed = bench._bench_fixed(
        HeatConfig(nx=32, ny=32, steps=10, backend="jnp"), budget_s=0.2
    )
    assert elapsed > 0
    elapsed_c, res = bench._bench_converge(
        HeatConfig(nx=32, ny=32, steps=10, converge=True,
                   check_interval=5, backend="jnp"), repeats=1
    )
    assert elapsed_c > 0
    assert res.steps_run <= 10
    assert np.isfinite(res.to_numpy()).all()
