"""Smoke tests of the driver-facing entry points on the CPU mesh."""

import sys

import jax
import numpy as np


def _repo_on_path():
    root = __file__.rsplit("/tests/", 1)[0]
    if root not in sys.path:
        sys.path.insert(0, root)


def test_graft_entry_compiles_and_runs():
    _repo_on_path()
    import __graft_entry__ as g

    fn, args = g.entry()
    new, res = jax.jit(fn)(*args)
    assert new.shape == args[0].shape
    assert float(res) > 0  # initial condition is not a fixed point


def test_dryrun_multichip_8():
    _repo_on_path()
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_bench_helper_on_tiny_config(monkeypatch):
    _repo_on_path()
    import bench
    from parallel_heat_tpu import HeatConfig
    from parallel_heat_tpu.utils import measure

    # _bench_fixed rides chain_slope, which RAISES on a non-positive
    # slope — at this tiny config the per-call compute is sub-ms, so
    # under full-suite load real wall-clock noise can invert the two
    # endpoint timings and flake the whole tier-1 run (seen round 14).
    # This test covers the helper's PLUMBING (runner build, warmup, rep
    # sizing, slope math), not the machine's scheduler: a deterministic
    # clock model makes it load-free, exactly like the calibrated_slope
    # tests in test_aux.py. The real-noise protocol stays covered where
    # it belongs — bench.py's own artifact runs.
    # The protocol lives in utils/measure.py now and bench resolves it
    # from there at call time, so the stub targets the measure module
    # and absorbs the clock= plumbing kwarg.
    def fake_chain_time(step_fn, u0, reps, per=1e-4, floor=0.05, **kw):
        return floor + per * reps

    monkeypatch.setattr(measure, "chain_time", fake_chain_time)
    monkeypatch.setattr(bench, "_sync_floor", lambda u0: 0.05)
    elapsed = bench._bench_fixed(
        HeatConfig(nx=32, ny=32, steps=10, backend="jnp"), budget_s=0.2
    )
    assert abs(elapsed - 1e-4) < 1e-12
    elapsed_c, res = bench._bench_converge(
        HeatConfig(nx=32, ny=32, steps=10, converge=True,
                   check_interval=5, backend="jnp"), repeats=1
    )
    assert elapsed_c > 0
    assert res.steps_run <= 10
    assert np.isfinite(res.to_numpy()).all()
