"""Auxiliary subsystems: profiling helpers, distributed runtime wrapper."""

import numpy as np
import pytest

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.parallel import distributed as dist
from parallel_heat_tpu.utils.profiling import (
    StepStats,
    Timeline,
    step_stats,
    sync,
    trace,
)


def test_step_stats_summary():
    cfg = HeatConfig(nx=32, ny=32, steps=10, backend="jnp")
    res = solve(cfg)
    st = step_stats(res, cfg)
    assert st.cells == 1024 and st.steps == 10
    assert st.mcells_steps_per_s > 0
    assert "steps/s" in st.summary()


def test_stats_bf16_bytes():
    cfg = HeatConfig(nx=32, ny=32, steps=4, dtype="bfloat16", backend="jnp")
    st = step_stats(solve(cfg), cfg)
    assert st.bytes_per_cell == 4  # read+write of 2-byte cells


def test_trace_writes_profile(tmp_path):
    cfg = HeatConfig(nx=16, ny=16, steps=3, backend="jnp")
    with trace(tmp_path / "prof") as done:
        res = solve(cfg)
        done(res.grid)
    sync(res.grid)  # also exercises the element-indexed flush
    sync(res)       # and the HeatResult overload
    files = list((tmp_path / "prof").rglob("*"))
    assert files, "profiler trace produced no files"


def test_timeline():
    tl = Timeline()
    tl.mark("init")
    tl.mark("run")
    s = tl.summary()
    assert "init" in s and "run" in s and "total" in s


def test_distributed_single_process():
    dist.initialize()  # no-op single process
    pid, count = dist.process_info()
    assert pid == 0 and count == 1
    shape = dist.suggest_mesh_shape(2)
    assert len(shape) == 2 and shape[0] * shape[1] == 8  # 8 CPU devices


def test_calibrated_slope_sizing_and_refusal(monkeypatch):
    # The calibration must size the long endpoint to hold span_s of
    # device work (computed from a two-point slope that cancels the
    # dispatch floor), and must REFUSE rather than return a garbage
    # rate when even max_reps cannot fill ~60% of the span.
    from parallel_heat_tpu.utils import measure
    from parallel_heat_tpu.utils import profiling as prof

    calls = []

    # The protocol lives in utils/measure.py now (profiling re-exports
    # it), so the stub targets the measure module and absorbs the
    # clock= plumbing kwarg.
    def fake_chain_time(fn, u0, reps, per=1e-3, floor=0.2, **kw):
        calls.append(reps)
        return floor + per * reps

    monkeypatch.setattr(measure, "chain_time", fake_chain_time)
    per = prof.calibrated_slope(None, None, span_s=0.5)
    assert abs(per - 1e-3) < 1e-12
    # endpoints: 1, 33 (calibration), then 1 and ~501 (the span)
    assert calls[:2] == [1, 33] and calls[-1] >= 1 + int(0.5 / 1e-3)

    calls.clear()
    monkeypatch.setattr(
        measure, "chain_time",
        lambda fn, u0, reps, **kw: 0.2 + 1e-3 * reps)
    with pytest.raises(RuntimeError, match="max_reps|span"):
        prof.calibrated_slope(None, None, span_s=10.0, max_reps=100)


def test_calibrated_slope_paired_interleaves(monkeypatch):
    # Paired mode must interleave the variants' endpoint batches (the
    # whole point: clock drift lands on every variant alike) and map a
    # non-positive slope to None instead of a garbage rate.
    from parallel_heat_tpu.utils import measure
    from parallel_heat_tpu.utils import profiling as prof

    seq = []

    def fake_chain_time(fn, u0, reps, **kw):
        seq.append((fn, reps))
        return 0.2 + fn * reps  # fn doubles as the per-call time

    monkeypatch.setattr(measure, "chain_time", fake_chain_time)
    out = prof.calibrated_slope_paired({ "a": 1e-3, "b": 2e-3 },
                                       None, span_s=0.1, batches=2)
    assert abs(out["a"] - 1e-3) < 1e-12
    assert abs(out["b"] - 2e-3) < 1e-12
    # after the 4 calibration calls, batches interleave a,b,a,b
    body = [fn for fn, _ in seq[4:]]
    assert body == [1e-3, 1e-3, 2e-3, 2e-3, 1e-3, 1e-3, 2e-3, 2e-3]

    monkeypatch.setattr(measure, "chain_time",
                        lambda fn, u0, reps, **kw: 0.5)  # flat: zero slope
    out = prof.calibrated_slope_paired({"a": None}, None, batches=1)
    assert out["a"] is None


def test_scored_mesh_factorization_avoids_z():
    # The kernel cost model prices the z lane-pad asymmetry (sharding
    # z pads the exchanged tail to the 128-lane tile): at hardware-
    # sized grids the scored 3D factorization must leave z unsharded
    # (measured +20-40% per device vs the balanced (2,2,2) at 512^3/8)
    # and fall back to the balanced pick where no Mosaic schedule
    # exists.
    from parallel_heat_tpu.parallel.mesh import (pick_mesh_shape,
                                                 pick_mesh_shape_scored)

    m = pick_mesh_shape_scored(8, (512, 512, 512))
    assert m[2] == 1 and m[0] * m[1] == 8
    m16 = pick_mesh_shape_scored(16, (512, 512, 512))
    assert m16[2] == 1 and m16[0] * m16[1] == 16
    # tiny grids: no schedule -> balanced fallback
    assert pick_mesh_shape_scored(8, (16, 16, 16)) == \
        pick_mesh_shape(8, 3)
    # 2D scored (round 4): the wide-row penalty picks the MEASURED
    # best (2,4) at the 32768^2 bf16 north star (G-uni 186.6 vs the
    # transpose's 173.7 Gcells*steps/s/device), where the balanced
    # pick chose the transpose; the (8,1) decomposition past the bf16
    # spill cliff is never offered. The f32 16384^2 pick is
    # model-driven (both its shapes sit under the width knee); pinned
    # so a model change is a visible decision, not drift.
    assert pick_mesh_shape_scored(8, (32768, 32768), "bfloat16") == (2, 4)
    assert pick_mesh_shape_scored(8, (16384, 16384)) == (4, 2)
    # unaligned 2D extents: no feasible factorization -> loud fallback
    import warnings as _w
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        assert pick_mesh_shape_scored(8, (200, 200)) == \
            pick_mesh_shape(8, 2)
    assert any("fall" in str(r.message) for r in rec)
    # grid-aware suggest_mesh_shape routes through the scored picker
    assert dist.suggest_mesh_shape(3, (512, 512, 512))[2] == 1
    assert dist.suggest_mesh_shape(2, (32768, 32768),
                                   "bfloat16") == (2, 4)


def test_scored_2d_mesh_solve_equivalence():
    # A solve on the scored 2D mesh agrees with the single-device
    # solve to f32 ulps (the scored pick changes the decomposition;
    # at this geometry the single-device path runs kernel A while the
    # blocks run kernel G, whose different chunk shapes shift XLA's
    # FMA contraction by ulps — the same precision contract as the 3D
    # band kernels). Bitwise equality across the G-variant chain at a
    # fixed mesh is pinned by test_temporal.
    import numpy as np

    from parallel_heat_tpu import HeatConfig, solve
    from parallel_heat_tpu.parallel.mesh import pick_mesh_shape_scored

    kw = dict(nx=64, ny=1024, steps=17, backend="pallas")
    mesh = pick_mesh_shape_scored(8, (64, 1024))
    assert mesh[0] * mesh[1] == 8
    single = solve(HeatConfig(**kw)).to_numpy()
    sharded = solve(HeatConfig(mesh_shape=mesh, halo_depth=8,
                               **kw)).to_numpy()
    np.testing.assert_allclose(single, sharded, rtol=1e-6, atol=0)


def test_gather_to_host_single_process():
    cfg = HeatConfig(nx=16, ny=16, steps=2, backend="jnp",
                     mesh_shape=(2, 4))
    res = solve(cfg)
    arr = dist.gather_to_host(res.grid)
    assert isinstance(arr, np.ndarray) and arr.shape == (16, 16)
