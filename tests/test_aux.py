"""Auxiliary subsystems: profiling helpers, distributed runtime wrapper."""

import numpy as np

from parallel_heat_tpu import HeatConfig, solve
from parallel_heat_tpu.parallel import distributed as dist
from parallel_heat_tpu.utils.profiling import (
    StepStats,
    Timeline,
    step_stats,
    sync,
    trace,
)


def test_step_stats_summary():
    cfg = HeatConfig(nx=32, ny=32, steps=10, backend="jnp")
    res = solve(cfg)
    st = step_stats(res, cfg)
    assert st.cells == 1024 and st.steps == 10
    assert st.mcells_steps_per_s > 0
    assert "steps/s" in st.summary()


def test_stats_bf16_bytes():
    cfg = HeatConfig(nx=32, ny=32, steps=4, dtype="bfloat16", backend="jnp")
    st = step_stats(solve(cfg), cfg)
    assert st.bytes_per_cell == 4  # read+write of 2-byte cells


def test_trace_writes_profile(tmp_path):
    cfg = HeatConfig(nx=16, ny=16, steps=3, backend="jnp")
    with trace(tmp_path / "prof") as done:
        res = solve(cfg)
        done(res.grid)
    sync(res.grid)  # also exercises the element-indexed flush
    sync(res)       # and the HeatResult overload
    files = list((tmp_path / "prof").rglob("*"))
    assert files, "profiler trace produced no files"


def test_timeline():
    tl = Timeline()
    tl.mark("init")
    tl.mark("run")
    s = tl.summary()
    assert "init" in s and "run" in s and "total" in s


def test_distributed_single_process():
    dist.initialize()  # no-op single process
    pid, count = dist.process_info()
    assert pid == 0 and count == 1
    shape = dist.suggest_mesh_shape(2)
    assert len(shape) == 2 and shape[0] * shape[1] == 8  # 8 CPU devices


def test_scored_mesh_factorization_avoids_z():
    # The kernel cost model prices the z lane-pad asymmetry (sharding
    # z pads the exchanged tail to the 128-lane tile): at hardware-
    # sized grids the scored 3D factorization must leave z unsharded
    # (measured +20-40% per device vs the balanced (2,2,2) at 512^3/8)
    # and fall back to the balanced pick where no Mosaic schedule
    # exists.
    from parallel_heat_tpu.parallel.mesh import (pick_mesh_shape,
                                                 pick_mesh_shape_scored)

    m = pick_mesh_shape_scored(8, (512, 512, 512))
    assert m[2] == 1 and m[0] * m[1] == 8
    m16 = pick_mesh_shape_scored(16, (512, 512, 512))
    assert m16[2] == 1 and m16[0] * m16[1] == 16
    # tiny grids: no schedule -> balanced fallback
    assert pick_mesh_shape_scored(8, (16, 16, 16)) == \
        pick_mesh_shape(8, 3)
    # 2D passthrough
    assert pick_mesh_shape_scored(8, (512, 512)) == pick_mesh_shape(8, 2)
    # grid-aware suggest_mesh_shape routes through the scored picker
    assert dist.suggest_mesh_shape(3, (512, 512, 512))[2] == 1


def test_gather_to_host_single_process():
    cfg = HeatConfig(nx=16, ny=16, steps=2, backend="jnp",
                     mesh_shape=(2, 4))
    res = solve(cfg)
    arr = dist.gather_to_host(res.grid)
    assert isinstance(arr, np.ndarray) and arr.shape == (16, 16)
