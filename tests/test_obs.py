"""Flight recorder: the journal-backed time-series metrics plane.

What is pinned here (ISSUE 18's acceptance surface):

- the series reducer obeys the incremental fold law at EVERY journal
  cut — ``reduce(prefix) then reduce(suffix, state) ==
  reduce(prefix + suffix)`` — including both downsampling tiers, which
  is what makes the snapshot/delta recovery exact by construction;
- crash-window recovery: a torn delta tail, deltas newer than the
  snapshot, compaction residue older than it, and a SIGKILLed live
  recorder all recover to the same state a clean fold produces;
- OpenMetrics exposition validated line-by-line against the format's
  grammar (TYPE before samples, contiguous families, ``_total`` on
  counters, terminal ``# EOF``);
- regression alerts: true-positive AND true-negative against a
  doctored tuning DB, with the journal latch holding exactly one
  ``alert_tripped`` across re-evaluations;
- observation-only: running the whole obs machinery between two
  identical solves changes neither the bits of the result nor the
  ``_build_runner`` miss count.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from parallel_heat_tpu.obs.alerts import (
    AlertEngine,
    AlertPolicy,
    reduce_alerts,
    tune_expectation,
)
from parallel_heat_tpu.obs.expo import (
    CONTENT_TYPE,
    ExpoServer,
    render_openmetrics,
    write_textfile,
)
from parallel_heat_tpu.obs.series import (
    M1_BUCKET_S,
    RAW_CAP,
    Recorder,
    _bucket_fold,
    load_state,
    obs_dir_for,
    reduce_obs,
    summarize_window,
)
from parallel_heat_tpu.service.store import JobStore, read_journal_file

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_ROOT, "tools")
_T0 = 1_700_000_000.0

# Injected topology: the tune-key join must work without devices (the
# alert engine runs on an ops box, not the TPU host).
_TOPO = {"platform": "cpu", "device_kind": "fixture", "n_devices": 1}


def _s(t, counter, value, kind="counter", host="h", part="p0"):
    return {"t": t, "host": host, "part": part, "counter": counter,
            "kind": kind, "value": value}


def _h(t, samples, cursors=None):
    return {"schema": 1, "event": "harvest", "t": t,
            "samples": samples, "cursors": cursors or {"parts": {}}}


def _mixed_events():
    """Harvest events spanning raw points, several 1-minute buckets
    and two 1-hour buckets, over two series kinds."""
    out = []
    for i in range(24):
        t = _T0 + i * 400.0  # crosses m1 buckets every event, h1 twice
        out.append(_h(t, [
            _s(t, "completed", 1 + (i % 3)),
            _s(t + 1, "steps_per_s", 100.0 + 10 * i, kind="gauge"),
            _s(t + 2, "queue_wait_s", 0.5 * i, kind="gauge",
               host="g", part="p1"),
        ], cursors={"parts": {"p0": {"journal": 10 * i}}}))
    return out


def _dumps(state):
    return json.dumps(state, sort_keys=True)


# ---------------------------------------------------------------------------
# The pure fold
# ---------------------------------------------------------------------------

def test_obs_fold_law_every_cut():
    events = _mixed_events()
    want = _dumps(reduce_obs(events))
    for cut in range(len(events) + 1):
        state = reduce_obs(events[:cut])
        reduce_obs(events[cut:], state)
        assert _dumps(state) == want, f"fold law broke at cut {cut}"


def test_obs_counter_cumulative_gauge_raw():
    ev = [_h(_T0, [_s(_T0, "completed", 2)]),
          _h(_T0 + 5, [_s(_T0 + 5, "completed", 3),
                       _s(_T0 + 5, "steps_per_s", 123.0, kind="gauge")])]
    st = reduce_obs(ev)
    cser = st["series"]["h|p0|completed"]
    # Counter samples carry INCREMENTS; the fold owns cumulative.
    assert [v for _t, v in cser["raw"]] == [2.0, 5.0]
    gser = st["series"]["h|p0|steps_per_s"]
    assert gser["kind"] == "gauge"
    assert [v for _t, v in gser["raw"]] == [123.0]
    assert st["n_samples"] == 3 and st["n_harvests"] == 2
    # Cursors: last harvest line wins (commit-together semantics).
    st2 = reduce_obs([_h(_T0 + 9, [], cursors={"parts": {"x": 1}})], st)
    assert st2["cursors"] == {"parts": {"x": 1}}


def test_obs_rollup_bucket_fold_tiers():
    st = reduce_obs([_h(_T0, [
        _s(_T0 + 1, "steps_per_s", 10.0, kind="gauge"),
        _s(_T0 + 2, "steps_per_s", 30.0, kind="gauge"),
        _s(_T0 + 61, "steps_per_s", 20.0, kind="gauge"),
    ])])
    ser = st["series"]["h|p0|steps_per_s"]
    assert len(ser["m1"]) == 2  # two distinct 1-minute buckets
    agg = ser["m1"][0][1]
    assert agg == {"min": 10.0, "max": 30.0, "sum": 40.0, "count": 2,
                   "last": 30.0}
    assert len(ser["h1"]) == 1  # one hour bucket holds all three
    assert ser["h1"][0][1]["count"] == 3
    # The m1 bucket time is the floor of the sample time.
    assert ser["m1"][0][0] == (_T0 + 1) // M1_BUCKET_S * M1_BUCKET_S


def test_obs_bucket_fold_cap_and_late_samples():
    buckets = []
    for i in range(5):
        _bucket_fold(buckets, 60.0 * i, float(i), cap=3)
    assert [b[0] for b in buckets] == [120.0, 180.0, 240.0]
    # Late sample into a RETAINED bucket merges...
    _bucket_fold(buckets, 180.0, 99.0, cap=3)
    assert buckets[1][1]["max"] == 99.0 and buckets[1][1]["count"] == 2
    # ...into a trimmed/never-created bucket drops (the ring never
    # reorders).
    before = _dumps(buckets)
    _bucket_fold(buckets, 0.0, 7.0, cap=3)
    _bucket_fold(buckets, 150.0, 7.0, cap=3)
    assert _dumps(buckets) == before


def test_obs_raw_cap():
    samples = [_s(_T0 + i, "completed", 1) for i in range(RAW_CAP + 40)]
    st = reduce_obs([_h(_T0, samples)])
    ser = st["series"]["h|p0|completed"]
    assert len(ser["raw"]) == RAW_CAP
    # The cumulative total survives the trim: the newest point carries
    # the full count even though the oldest raw points are gone.
    assert ser["raw"][-1][1] == RAW_CAP + 40
    assert st["n_samples"] == RAW_CAP + 40


def test_obs_foreign_samples_ignored():
    st = reduce_obs([
        {"event": "not_harvest", "samples": [_s(_T0, "completed", 1)]},
        _h(_T0, [{"counter": "completed"},  # no t/value
                 {"t": float("nan"), "counter": "x", "value": 1},
                 "not-a-dict", None,
                 _s(_T0, "completed", 1)]),
    ])
    assert st["n_samples"] == 1 and len(st["series"]) == 1


# ---------------------------------------------------------------------------
# Recorder: harvest + delta journal + snapshot compaction
# ---------------------------------------------------------------------------

def _queue_with_jobs(tmp_path, n=3, name="q"):
    root = str(tmp_path / name)
    store = JobStore(root, create=True)
    j = store.journal
    for k in range(n):
        jid = f"j{k}"
        j.append("accepted", job_id=jid, t_wall=_T0 + 10 * k,
                 hbm_bytes=1, host="hosta")
        j.append("dispatched", job_id=jid, t_wall=_T0 + 10 * k + 1,
                 worker=f"w{k}", attempt=1, host="hosta")
        j.append("completed", job_id=jid, t_wall=_T0 + 10 * k + 2,
                 host="hosta")
    j.close()
    return root


def test_recorder_poll_idempotent_and_reload(tmp_path):
    root = _queue_with_jobs(tmp_path)
    with Recorder(root) as r:
        n = r.poll(now=_T0 + 100, compact=False)
        assert n > 0
        # Nothing new on disk -> nothing harvested (cursor discipline).
        assert r.poll(now=_T0 + 101, compact=False) == 0
        ser = r.state["series"]["hosta||completed"]
        assert ser["raw"][-1][1] == 3.0
        # queue_wait_s gauge: accepted -> first dispatch.
        wait = r.state["series"]["hosta||queue_wait_s"]
        assert [v for _t, v in wait["raw"]] == [1.0, 1.0, 1.0]
        live = _dumps(r.state)
    state, _gen = load_state(obs_dir_for(root))
    assert _dumps(state) == live


def test_recorder_compaction_equivalence(tmp_path):
    root = _queue_with_jobs(tmp_path, n=2)
    with Recorder(root) as r:
        r.poll(now=_T0 + 50, compact=False)
        before = _dumps(r.state)
        gen0 = r.gen
        r.compact()
        assert r.gen == gen0 + 1
        assert _dumps(r.state) == before
    # Reload reads snapshot + (empty) new-gen deltas.
    state, gen = load_state(obs_dir_for(root))
    assert _dumps(state) == before and gen == gen0 + 1
    # More activity after compaction folds on top.
    store = JobStore(root, create=False)
    store.journal.append("accepted", job_id="late", t_wall=_T0 + 60,
                         hbm_bytes=1, host="hosta")
    store.journal.close()
    with Recorder(root) as r2:
        r2.poll(now=_T0 + 70, compact=False)
        assert r2.state["series"]["hosta||jobs_accepted"]["raw"][-1][1] \
            == 3.0


def test_recorder_crash_windows(tmp_path):
    root = _queue_with_jobs(tmp_path)
    obs = obs_dir_for(root)
    with Recorder(root) as r:
        r.poll(now=_T0 + 100, compact=False)
        clean = _dumps(r.state)
        gen = r.gen
    # Window 1: torn final delta line (killed mid-append) — the torn
    # tail is invisible, the prefix state stands.
    delta = os.path.join(obs, f"deltas.{gen:08d}.jsonl")
    with open(delta, "ab") as f:
        f.write(b'{"event": "harvest", "t": 1, "samples": [{"t": 1,')
    state, _ = load_state(obs)
    assert _dumps(state) == clean
    with open(delta, "rb") as f:
        data = f.read()
    with open(delta, "wb") as f:
        f.write(data[:data.rfind(b"{")])
    # Window 2: compaction crashed AFTER the snapshot rename but
    # BEFORE the old delta unlink — stale deltas are ignored by
    # generation, not double-folded.
    snap_state, _ = load_state(obs)
    with open(os.path.join(obs, "snapshot.json"), "w") as f:
        json.dump({"schema": 1, "gen": gen + 1, "state": snap_state},
                  f)
    state2, gen2 = load_state(obs)
    assert _dumps(state2) == clean and gen2 == gen + 1
    # Window 3: snapshot itself torn -> full delta refold.
    with open(os.path.join(obs, "snapshot.json"), "w") as f:
        f.write('{"schema": 1, "gen": ')
    state3, _ = load_state(obs)
    assert _dumps(state3) == clean


def test_recorder_sigkill_recovery(tmp_path):
    """A live recorder SIGKILLed mid-poll recovers by construction:
    whatever prefix of harvest lines hit the disk folds to a valid
    state, a restarted recorder continues from it, and re-harvest
    never double-counts a source line."""
    root = _queue_with_jobs(tmp_path, n=5)
    code = (
        "import sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from parallel_heat_tpu.obs.series import Recorder\n"
        "r = Recorder(%r)\n"
        "print('ready', flush=True)\n"
        "i = 0\n"
        "while True:\n"
        "    r.poll(now=%r + i, compact=(i %% 7 == 6))\n"
        "    i += 1\n" % (_ROOT, root, _T0))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, env=env)
    try:
        assert proc.stdout.readline().strip() == b"ready"
        time.sleep(0.5)
    finally:
        proc.kill()
        proc.wait(timeout=30)
    state, _gen = load_state(obs_dir_for(root))
    # Exactly the journal's activity, counted once: 5 jobs' worth of
    # counters regardless of how many polls/compactions ran.
    assert state["series"]["hosta||completed"]["raw"][-1][1] == 5.0
    assert state["series"]["hosta||dispatches"]["raw"][-1][1] == 5.0
    # A restarted recorder resumes from the recovered cursors: nothing
    # new on disk means nothing harvested.
    with Recorder(root) as r:
        assert _dumps(r.state) == _dumps(state)
        assert r.poll(now=_T0 + 999, compact=False) == 0


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge)$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) \S.*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" -?[0-9][0-9.eE+-]*$")


def test_openmetrics_grammar():
    st = reduce_obs(_mixed_events())
    text = render_openmetrics(st)
    lines = text.splitlines()
    assert lines[-1] == "# EOF" and text.endswith("# EOF\n")
    declared = {}   # family -> kind
    seen_samples = set()
    current = None
    for ln in lines[:-1]:
        m = _TYPE_RE.match(ln)
        if m:
            name, kind = m.groups()
            # Families are contiguous and declared once.
            assert name not in declared, f"re-declared family {name}"
            assert name not in seen_samples
            declared[name] = kind
            current = name
            continue
        m = _HELP_RE.match(ln)
        if m:
            assert m.group(1) == current
            continue
        m = _SAMPLE_RE.match(ln)
        assert m, f"line fails the exposition grammar: {ln!r}"
        sample_name = m.group(1)
        fam = (sample_name[:-len("_total")]
               if sample_name.endswith("_total") else sample_name)
        assert fam == current, f"interleaved family at {ln!r}"
        # Counter samples carry _total; gauges must not.
        if declared[fam] == "counter":
            assert sample_name.endswith("_total"), ln
        else:
            assert not sample_name.endswith("_total"), ln
        seen_samples.add(fam)
    assert "heat_completed" in declared
    assert declared["heat_completed"] == "counter"
    assert declared["heat_steps_per_s"] == "gauge"
    assert declared["heat_obs_samples"] == "counter"


def test_openmetrics_label_escaping_and_values():
    st = reduce_obs([_h(_T0, [
        _s(_T0, "completed", 2, host='we"ird\\h'),
        _s(_T0, "steps_per_s", 1234.5, kind="gauge"),
    ])])
    text = render_openmetrics(st)
    assert 'host="we\\"ird\\\\h"' in text
    # Integral counters render without a trailing .0.
    assert re.search(r'^heat_completed_total\{[^}]*\} 2$', text,
                     re.M), text
    assert "1234.5" in text


def test_expo_textfile_and_server(tmp_path):
    st = reduce_obs(_mixed_events())
    text = render_openmetrics(st)
    path = str(tmp_path / "metrics.prom")
    write_textfile(path, text)
    with open(path) as f:
        assert f.read() == text
    server = ExpoServer(lambda: text, bind="127.0.0.1", port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            assert resp.read().decode() == text
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Windowed summaries
# ---------------------------------------------------------------------------

def test_summarize_window():
    st = reduce_obs([
        _h(_T0, [_s(_T0, "completed", 4),
                 _s(_T0, "steps_per_s", 100.0, kind="gauge")]),
        _h(_T0 + 100, [_s(_T0 + 100, "completed", 6),
                       _s(_T0 + 100, "cache_hits", 3),
                       _s(_T0 + 100, "steps_per_s", 300.0,
                          kind="gauge")]),
    ])
    full = summarize_window(st)
    assert full["completed"] == 10.0 and full["cache_hits"] == 3.0
    assert full["cache_hit_rate"] == pytest.approx(0.3)
    assert full["steps_per_s"]["max"] == 300.0
    assert full["steps_per_s"]["n"] == 2
    # Window covering only the second harvest: counter DELTAS, not
    # totals; gauge percentiles over windowed samples only.
    win = summarize_window(st, _T0 + 50, _T0 + 200)
    assert win["completed"] == 6.0
    assert win["cache_hit_rate"] == pytest.approx(0.5)
    assert win["steps_per_s"]["n"] == 1
    assert win["steps_per_s"]["last"] == 300.0
    # Empty window: zero deltas, unmeasured rate.
    empty = summarize_window(st, _T0 + 500, _T0 + 600)
    assert empty["completed"] == 0.0
    assert empty["cache_hit_rate"] is None
    assert "steps_per_s" not in empty


# ---------------------------------------------------------------------------
# Alerts: tuned-baseline regression + trends + the journal latch
# ---------------------------------------------------------------------------

def _doctored_tune_db(tmp_path, config, min_wall_s=0.1,
                      steps_per_call=1000, verified=True):
    """A tuning DB holding one measured winner for ``config``'s tune
    key: expectation = steps_per_call / min_wall_s steps/s."""
    from parallel_heat_tpu.tune.db import TuneDB

    geometry = {"shape": [config["nx"], config["ny"]],
                "dtype": str(config.get("dtype") or "float32"),
                "accumulate": str(config.get("accumulate")
                                  or "storage")}
    db_root = str(tmp_path / "tunedb")
    with TuneDB(db_root) as db:
        db.put("single_2d", _TOPO, geometry, choice="A",
               verified=verified,
               candidates=[{"choice": "A", "feasible": True,
                            "bitwise_verified": True,
                            "min_wall_s": min_wall_s}],
               protocol={"timer": "fixture", "rounds": 1,
                         "steps_per_call": steps_per_call,
                         "reference": "jnp"})
    return db_root


def _job_with_throughput(root, jid, config, sps, t0, n_chunks=4):
    """One dispatched+completed job whose committed spec is ``config``
    and whose observed steps_per_s series sits at ``sps``."""
    store = JobStore(root, create=False) if os.path.isdir(root) \
        else JobStore(root, create=True)
    with open(os.path.join(root, "jobs", f"{jid}.json"), "w") as f:
        json.dump({"job_id": jid, "config": config}, f)
    j = store.journal
    j.append("accepted", job_id=jid, t_wall=t0, hbm_bytes=1)
    j.append("dispatched", job_id=jid, t_wall=t0 + 1, worker=f"w-{jid}",
             attempt=1)
    j.append("completed", job_id=jid, t_wall=t0 + 20)
    j.close()
    samples = [_s(t0 + 2 + i * 4, "steps_per_s", sps, kind="gauge",
                  host="", part="") for i in range(n_chunks)]
    return _h(t0 + 21, samples)


_CFG = {"nx": 32, "ny": 32, "steps": 100, "backend": "jnp"}


def test_tune_expectation_join(tmp_path):
    db_root = _doctored_tune_db(tmp_path, _CFG, min_wall_s=0.1,
                                steps_per_call=1000)
    assert tune_expectation(_CFG, db_root, topology=_TOPO) \
        == pytest.approx(10_000.0)
    # Different geometry -> different key -> no baseline.
    other = dict(_CFG, nx=64)
    assert tune_expectation(other, db_root, topology=_TOPO) is None
    # 3D and malformed configs carry no baseline.
    assert tune_expectation(dict(_CFG, nz=8), db_root,
                            topology=_TOPO) is None
    assert tune_expectation({"nx": "x"}, db_root,
                            topology=_TOPO) is None
    # An unverified entry is refused (measured-only-after-bitwise).
    db2 = _doctored_tune_db(tmp_path / "u", _CFG, verified=False)
    assert tune_expectation(_CFG, db2, topology=_TOPO) is None


def test_perf_regression_tp_tn_and_latch(tmp_path):
    root = str(tmp_path / "q")
    JobStore(root, create=True)
    db_root = _doctored_tune_db(tmp_path, _CFG, min_wall_s=0.1,
                                steps_per_call=1000)  # expect 10k
    ev_slow = _job_with_throughput(root, "slow", _CFG, sps=1000.0,
                                   t0=_T0)          # 10% of tuned: TP
    ev_fast = _job_with_throughput(root, "fast", _CFG, sps=9000.0,
                                   t0=_T0 + 100)    # 90%: TN
    state = reduce_obs([ev_slow, ev_fast])
    with AlertEngine(obs_dir_for(root)) as eng:
        tripped = eng.evaluate(state, root=root, tune_db=db_root,
                               topology=_TOPO, now=_T0 + 200)
        assert [a["key"] for a in tripped] == \
            ["perf_regression||slow"]
        d = tripped[0]["detail"]
        assert d["expected_steps_per_s"] == pytest.approx(10_000.0)
        assert d["observed_steps_per_s"] == pytest.approx(1000.0)
        # The latch: the same (still-true) condition trips nothing
        # new, and never clears — exactly one journaled trip, ever.
        for _ in range(3):
            assert eng.evaluate(state, root=root, tune_db=db_root,
                                topology=_TOPO, now=_T0 + 300) == []
        active = eng.active()
        assert set(active) == {"perf_regression||slow"}
    events, _bad, _torn = read_journal_file(
        os.path.join(obs_dir_for(root), "alerts.jsonl"))
    assert sum(1 for e in events
               if e.get("event") == "alert_tripped") == 1


def test_perf_regression_needs_samples_and_baseline(tmp_path):
    root = str(tmp_path / "q")
    JobStore(root, create=True)
    db_root = _doctored_tune_db(tmp_path, _CFG)
    # Too few windowed samples: no verdict (perf_min_samples).
    ev = _job_with_throughput(root, "thin", _CFG, sps=10.0, t0=_T0,
                              n_chunks=2)
    state = reduce_obs([ev])
    with AlertEngine(obs_dir_for(root)) as eng:
        assert eng.evaluate(state, root=root, tune_db=db_root,
                            topology=_TOPO) == []
    # A config with no DB entry: silent (no alert without evidence).
    root2 = str(tmp_path / "q2")
    JobStore(root2, create=True)
    ev2 = _job_with_throughput(root2, "nokey", dict(_CFG, nx=48),
                               sps=10.0, t0=_T0)
    with AlertEngine(obs_dir_for(root2)) as eng:
        assert eng.evaluate(reduce_obs([ev2]), root=root2,
                            tune_db=db_root, topology=_TOPO) == []


def test_trend_alerts_trip_and_clear(tmp_path):
    obs = str(tmp_path / "obs")
    pol = AlertPolicy(wait_min_samples=4, wait_min_s=5.0,
                      wait_growth_factor=3.0, hb_max_age_s=30.0)
    grow = reduce_obs([_h(_T0, [
        _s(_T0 + i, "queue_wait_s", v, kind="gauge")
        for i, v in enumerate([1.0, 1.0, 20.0, 30.0])]
        + [_s(_T0 + 9, "daemon_hb_age_s", 45.0, kind="gauge")])])
    with AlertEngine(obs, policy=pol) as eng:
        kinds = {a["kind"] for a in eng.evaluate(grow)}
        assert kinds == {"queue_wait_growth", "heartbeat_gap"}
        # Recovery: waits flat again, heartbeat fresh -> trend alerts
        # CLEAR (unlike the per-job perf latch).
        calm = reduce_obs([_h(_T0 + 100, [
            _s(_T0 + 100 + i, "queue_wait_s", 1.0, kind="gauge")
            for i in range(4)]
            + [_s(_T0 + 109, "daemon_hb_age_s", 1.0, kind="gauge")])])
        assert eng.evaluate(calm) == []
        assert eng.active() == {}


def test_cache_hit_collapse_alert(tmp_path):
    obs = str(tmp_path / "obs")
    pol = AlertPolicy(cache_window_s=100.0, cache_min_completed=8,
                      cache_collapse_fraction=0.5)
    # History: 20 completions, 10 hits (rate .5); recent window: 10
    # completions, 0 hits -> collapse.
    ev = [_h(_T0, [_s(_T0, "completed", 10), _s(_T0, "cache_hits", 10)]),
          _h(_T0 + 300, [_s(_T0 + 300, "completed", 10)])]
    with AlertEngine(obs, policy=pol) as eng:
        tripped = eng.evaluate(reduce_obs(ev))
        assert [a["kind"] for a in tripped] == ["cache_hit_collapse"]


def test_alert_fold_law_and_anomalies():
    trip = {"event": "alert_tripped", "key": "k1", "kind": "x"}
    clear = {"event": "alert_cleared", "key": "k1"}
    events = [trip, clear, dict(trip, key="k2"), dict(trip, key="k2"),
              {"event": "alert_cleared", "key": "ghost"}]
    whole = reduce_alerts(events)
    state = reduce_alerts(events[:2])
    assert reduce_alerts(events[2:], state) == whole
    active, anomalies = whole
    assert set(active) == {"k2"}
    assert any("duplicate trip of k2" in a for a in anomalies)
    assert any("unlatched ghost" in a for a in anomalies)


# ---------------------------------------------------------------------------
# The observation-only pin
# ---------------------------------------------------------------------------

def test_obs_plane_is_observation_only(tmp_path):
    """Running the ENTIRE obs machinery between two identical solves
    changes nothing: bitwise-identical grids, zero new
    ``_build_runner`` misses."""
    from parallel_heat_tpu import HeatConfig, solve
    from parallel_heat_tpu.solver import _build_runner

    cfg = HeatConfig(nx=16, ny=16, steps=30, backend="jnp")
    before = np.asarray(solve(cfg).grid)
    misses = _build_runner.cache_info().misses

    root = _queue_with_jobs(tmp_path)
    db_root = _doctored_tune_db(tmp_path, _CFG)
    with Recorder(root) as r:
        r.poll(now=_T0 + 100)
        text = render_openmetrics(r.state)
        write_textfile(str(tmp_path / "m.prom"), text)
        summarize_window(r.state, _T0, _T0 + 100)
        with AlertEngine(r.obs_dir) as eng:
            eng.evaluate(r.state, root=root, tune_db=db_root,
                         topology=_TOPO)
        r.compact()

    after = np.asarray(solve(cfg).grid)
    assert before.tobytes() == after.tobytes()
    assert _build_runner.cache_info().misses == misses


# ---------------------------------------------------------------------------
# CLI + tools integration
# ---------------------------------------------------------------------------

def test_cli_metrics_serve_once(tmp_path, capsys):
    from parallel_heat_tpu.service.cli import main as heatd_main

    root = _queue_with_jobs(tmp_path)
    rc = heatd_main(["metrics-serve", "--root", root, "--once"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "series ->" in out
    prom = os.path.join(obs_dir_for(root), "metrics.prom")
    with open(prom) as f:
        text = f.read()
    assert "heat_completed_total" in text and text.endswith("# EOF\n")
    # Recorder heartbeat landed for monitor's down-vs-idle probe.
    with open(os.path.join(obs_dir_for(root), "recorder.json")) as f:
        assert json.load(f)["n_samples"] > 0


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.remove(_TOOLS)


def test_metrics_report_window_and_rollup(tmp_path, capsys):
    mr = _tool("metrics_report")
    now = time.time()
    root = str(tmp_path / "q")
    store = JobStore(root, create=True)
    j = store.journal
    for jid, base in (("old", now - 1000), ("new", now - 10)):
        j.append("accepted", job_id=jid, t_wall=base, hbm_bytes=1)
        j.append("dispatched", job_id=jid, t_wall=base + 1,
                 worker="w-" + jid, attempt=1)
        j.append("completed", job_id=jid, t_wall=base + 2)
    j.close()
    assert mr.main([root, "--json", "--since", "-60"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fleet"]["completed"] == 1  # old job outside window
    assert doc["window"]["since"] is not None
    # --rollup: same answers from the recorder's folded series.
    with Recorder(root) as r:
        r.poll(now=now)
    assert mr.main([root, "--rollup", "--json",
                    "--fail-on", "quarantined>0"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["completed"] == 2.0
    assert mr.main([root, "--rollup", "--json", "--since", "-60"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["completed"] == 1.0
    # Unknown ceilings stay loud in rollup mode too.
    assert mr.main([root, "--rollup", "--fail-on", "nonsense>0"]) == 1
    capsys.readouterr()


def test_slo_gate_window(tmp_path, capsys):
    sg = _tool("slo_gate")
    now = time.time()
    root = str(tmp_path / "q")
    store = JobStore(root, create=True)
    j = store.journal
    j.append("accepted", job_id="bad", t_wall=now - 1000, hbm_bytes=1)
    j.append("dispatched", job_id="bad", t_wall=now - 999, worker="w1",
             attempt=1)
    j.append("quarantined", job_id="bad", t_wall=now - 998,
             kind="poison", reason="fixture")
    j.append("accepted", job_id="ok", t_wall=now - 10, hbm_bytes=1)
    j.append("dispatched", job_id="ok", t_wall=now - 9, worker="w2",
             attempt=1)
    j.append("completed", job_id="ok", t_wall=now - 8)
    j.close()
    assert sg.main([root, "--fleet", "quarantined>0"]) == 2
    assert sg.main([root, "--fleet", "quarantined>0",
                    "--window", "60"]) == 0
    spec = str(tmp_path / "slo.json")
    with open(spec, "w") as f:
        json.dump({"fleet": ["quarantined>0"], "window_s": 60}, f)
    assert sg.main([root, "--spec", spec]) == 0
    # CLI --window overrides the spec's window_s.
    assert sg.main([root, "--spec", spec, "--window", "2000"]) == 2
    capsys.readouterr()


def test_monitor_obs_columns_and_recorder_down(tmp_path):
    mon = _tool("monitor")
    from parallel_heat_tpu.service import fleet as fleetmod

    now = time.time()
    froot = str(tmp_path / "fleet")
    fleetmod.fleet_init(froot, partitions=1, clock=lambda: now)
    pname, proot = fleetmod.partition_roots(froot)[0]
    store = JobStore(proot, create=False)
    j = store.journal
    for k in range(3):
        j.append("accepted", job_id=f"j{k}", t_wall=now - 30 + 10 * k,
                 hbm_bytes=1, host="hosta")
        j.append("completed", job_id=f"j{k}", t_wall=now - 29 + 10 * k,
                 host="hosta")
    j.close()
    with Recorder(froot) as r:
        r.poll(now=now)
        r.write_heartbeat(2.0, now=now)
    fs = mon.FleetState(froot)
    fs.poll()
    line = fs.render(now=now)
    # Fresh recorder + sparkline trend column: the live fleet view.
    assert "done:" in line and "obs hb" in line
    assert "(stale?)" not in line
    # Recorder down: heartbeat goes stale, the row says so — this is
    # what distinguishes a dead recorder from an idle fleet (whose
    # heartbeat stays fresh over flat sparklines).
    with Recorder(froot) as r:
        r.write_heartbeat(2.0, now=now - 300)
    fs2 = mon.FleetState(froot)
    fs2.poll()
    assert "(stale?)" in fs2.render(now=now)
